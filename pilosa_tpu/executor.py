"""Query executor: PQL call trees over batched, slice-stacked bitmaps.

Reference analog: executor.go (1305 LoC).  The reference maps every call
over slices with a goroutine per slice and per node (executor.go:1115-1244)
and reduces channel results.  Here the map phase over *local* slices is a
single batched evaluation: bitmap leaves gather dense rows into a
``uint32[n_slices, W]`` stack and each set-op/count applies to the whole
stack in one engine call (XLA kernel on TPU — the per-slice loop becomes a
vectorized axis, which is the TPU-native shape of the same mapReduce).

Remote slices (multi-node) go through ``self.cluster`` /
``self.client_factory`` exactly like the reference's remote exec
(executor.go:1009-1091): the call tree is forwarded with opt.remote=True
and the peer executes its own slice batch.

Dispatch table (executor.go:156-179): Bitmap, Intersect, Union,
Difference, Xor(n/a in reference v0 — kept local), Range, Count, TopN,
SetBit, ClearBit, SetRowAttrs, SetColumnAttrs.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict
from functools import partial
from dataclasses import dataclass, field, replace as dc_replace
from datetime import datetime
from typing import Any, Optional, Sequence

import numpy as np

from pilosa_tpu import native as native_mod
from pilosa_tpu import pql
from pilosa_tpu.analysis import lockcheck
from pilosa_tpu import qcache as qcache_mod
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.fragment import TopOptions
from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD
from pilosa_tpu.engine import new_engine
from pilosa_tpu.rowpool import DeviceRowPool, chunk_queries, pool_capacity
from pilosa_tpu.pilosa import (
    ErrFrameInverseDisabled,
    ErrFrameNotFound,
    ErrIndexNotFound,
    ErrQueryRequired,
    ErrTooManyWrites,
    PilosaError,
    SLICE_WIDTH,
)

# Frame used when a call doesn't specify one (executor.go:33-35).
DEFAULT_FRAME = "general"


_WORDS = SLICE_WIDTH // 32

# Device kernels accumulate counts in int32 (TPU jax runs x32; int64 in
# Pallas/VPU would be emulated): one dispatch may cover at most this many
# slices, since a full-density count is n_slices * 2^20 per query and
# 2047 * 2^20 < 2^31.  Wider spans chunk the slice axis and sum the
# per-chunk partials in int64 HOST-side (same bound as the Gram's
# _GRAM_SLICES_MAX; BASELINE.md round-3 addendum 3 measured the overflow).
_INT32_SAFE_SLICES = 2047


# --- fused tree compilation helpers (executor.go:261-276, fused) -----------
#
# An arbitrary nested Count tree compiles to a PERFECT binary tree:
# ``leaves`` = 2^D gathered row ids in-order, ``opc`` = 2^D - 1 internal
# node opcodes level-major bottom-up (ops.bitwise.gather_count_tree
# documents the encoding).  N-ary associative nodes (Intersect/Union/Xor)
# balance into log-depth subtrees; n-ary Difference rewrites as
# a &~ (b | c | ...) — identical to the left fold a &~ b &~ c.  PASS
# nodes (take the left child) pad odd arities and unbalanced nesting.

_TREE_OP_IDS = {"and": 0, "or": 1, "xor": 2, "andnot": 3}
_TREE_PASS = 4
# 16 leaves per query; deeper trees take the sequential path (a single
# PQL call nested past depth 4 is vanishingly rare — dashboards batch
# WIDE, not deep).
_TREE_DEPTH_MAX = 4


class _TreeUnfusable(Exception):
    """Tree shape outside the fused lane (not an error — sequential path)."""


# First frame reference in a request (double-quoted, single-quoted, or
# bare identifier) — picks the serve-state candidate in the fast lane.
_FRAME_SNIFF_RX = re.compile(
    r'frame\s*=\s*(?:"([a-z][a-z0-9_-]{0,64})"'
    r"|'([a-z][a-z0-9_-]{0,64})'"
    r"|([a-z][a-z0-9_-]{0,64}))"
)


def _group_sort_key(kv):
    """Deterministic dispatch order over mixed group keys: plain-op
    groups key on (op-string, arity); tree groups on ("tree", K)."""
    op, kb = kv[0]
    return (str(op[0]) if isinstance(op, tuple) else op, kb)


def _tree_depth(node) -> int:
    if isinstance(node, int):
        return 0
    return 1 + max(_tree_depth(node[1]), _tree_depth(node[2]))


def _tree_balanced(op_id: int, nodes: list):
    """Balanced combine under one associative op (the left-fold semantics
    of n-ary Intersect/Union/Xor are order-independent)."""
    while len(nodes) > 1:
        nxt = [
            (op_id, nodes[i], nodes[i + 1]) for i in range(0, len(nodes) - 1, 2)
        ]
        if len(nodes) % 2:
            nxt.append(nodes[-1])
        nodes = nxt
    return nodes[0]


def _tree_fill(d: int, fill: int):
    """A perfect PASS-subtree of depth d over the fill leaf."""
    if d == 0:
        return fill
    sub = _tree_fill(d - 1, fill)
    return (_TREE_PASS, sub, sub)


def _tree_pad(node, d: int, fill: int):
    """Pad a tree to PERFECT depth d (PASS nodes keep the left value)."""
    if d == 0:
        return node
    if isinstance(node, int):
        return (_TREE_PASS, _tree_pad(node, d - 1, fill), _tree_fill(d - 1, fill))
    return (node[0], _tree_pad(node[1], d - 1, fill), _tree_pad(node[2], d - 1, fill))


def _tree_flatten(node, d: int) -> tuple[list[int], list[int]]:
    """(leaves in-order, opcodes level-major bottom-up) of a perfect tree."""
    leaves: list[int] = []
    levels: list[list[int]] = [[] for _ in range(d)]

    def walk(n, h):
        if h == 0:
            leaves.append(n)
            return
        op, l, r = n
        levels[h - 1].append(op)  # DFS keeps each level left-to-right
        walk(l, h - 1)
        walk(r, h - 1)

    walk(node, d)
    return leaves, [o for lv in levels for o in lv]


@dataclass
class ExecOptions:
    """Execution options (executor.go ExecOptions)."""

    remote: bool = False
    exclude_attrs: bool = False
    # Request deadline (qos.Deadline): checked at cheap checkpoints
    # between calls and between fan-out slice chunks, and forwarded to
    # remote nodes as the remaining budget.  None = unbounded.
    deadline: Any = None
    # Per-request qcache bypass (X-Pilosa-No-Cache: the request neither
    # reads nor stores a query-result cache entry) — the A/B lever for
    # hit-rate measurement and stale-read debugging.
    no_cache: bool = False
    # Request trace span (trace.Span): the root the serving door opened
    # for a SAMPLED request.  None (the common case) keeps every
    # instrumentation site a single branch — the tracing-off path adds
    # no objects and no calls.
    span: Any = None
    # Strategy plan from the cost-based planner (planner.Planner
    # plan_for): {"fp", "lane", "src", "confidence"}, JSON-clean so the
    # lockstep service ships it on the batch wire entry like the expiry
    # and sampling flags — the executor APPLIES plans but never makes
    # them, so every rank runs rank 0's decision.  None (and a plan
    # whose lane is None) keeps the static strategy ladder bit-exact.
    plan: Any = None


class QueryBitmap:
    """A bitmap query result: per-slice dense segments + optional attrs.

    Reference analog: bitmap.go's segment-list Bitmap (bitmap.go:27-134).
    Segments map slice -> uint32[W] packed words in *slice-local* bit
    positions; global column = slice*SLICE_WIDTH + local position.
    """

    def __init__(self, segments: Optional[dict[int, np.ndarray]] = None, attrs: Optional[dict] = None):
        self.segments = segments or {}
        self.attrs = attrs or {}

    def bits(self) -> list[int]:
        out = []
        from pilosa_tpu.ops.bitwise import unpack_positions

        for slice_i in sorted(self.segments):
            pos = unpack_positions(self.segments[slice_i])
            out.extend((pos + np.uint64(slice_i * SLICE_WIDTH)).tolist())
        return out

    def count(self) -> int:
        from pilosa_tpu.roaring import _popcount_words

        return sum(_popcount_words(words) for words in self.segments.values())

    def merge(self, other: "QueryBitmap") -> "QueryBitmap":
        """OR-merge segments (distributed reduce; bitmap.go Merge)."""
        segs = dict(self.segments)
        for s, words in other.segments.items():
            segs[s] = (segs[s] | words) if s in segs else words
        out = QueryBitmap(segs, dict(self.attrs) or dict(other.attrs))
        return out

    def to_json(self) -> dict:
        return {"attrs": self.attrs, "bits": self.bits()}


BITMAP_CALLS = frozenset({"Bitmap", "Intersect", "Union", "Difference", "Xor", "Range"})


def needs_slices(calls: Sequence[pql.Call]) -> bool:
    return any(c.name in BITMAP_CALLS or c.name in ("Count", "TopN") for c in calls)


@lockcheck.guarded_class
class Executor:
    # Lockset race detector declarations: the device-state pools move
    # under their dedicated leaf locks.  These fields are containers
    # mutated in place, so the static guarded-fields rule carries most
    # of the enforcement (the runtime half sees rebinds only).
    _guarded_by_ = {
        "_matrix_cache": "executor._matrix_mu",
        "_multi_matrix_cache": "executor._matrix_mu",
        "_serve_states": "executor._matrix_mu",
        "_dirty_rows": "executor._dirty_mu",
        # Monotonic invalidation counter for the per-thread armed lane
        # tables (each thread's tables are private; only the epoch is
        # shared, written on frame/index drops).
        "_lane_epoch": "executor._matrix_mu",
    }

    def __init__(
        self,
        holder,
        engine: str = "auto",
        cluster=None,
        client_factory=None,
        host: str = "",
        max_writes_per_request: int = 0,
        write_queue: bool = False,
        serve_state_cache: int = 0,
        repair_rows_max: Optional[int] = None,
        gram_rows_max: int = 0,
        no_gram: Optional[bool] = None,
        stream_bytes: int = 0,
        slice_chunk: int = 0,
        matrix_cache_entries: int = 0,
        matrix_rows_max: int = 0,
        qcache: Any = "env",
        stats=None,
    ):
        self.holder = holder
        self.engine = new_engine(engine) if isinstance(engine, str) else engine
        self.cluster = cluster  # cluster.Cluster; None = single node
        self.client_factory = client_factory  # host -> client with .query()
        self.host = host
        self.max_writes_per_request = max_writes_per_request
        # Device-resident row matrices for the fused count-intersect path,
        # keyed by (index, frame, view, slices) and validated by per-fragment
        # write generations — steady-state fused requests cost zero
        # host→device row traffic.
        self._matrix_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        # Multi-view matrices for the fused Range path, keyed by
        # (index, frame, views, slices); validated the same way.
        self._multi_matrix_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._matrix_mu = lockcheck.named_lock("executor._matrix_mu")
        # Tuning-knob precedence, uniform across every routed knob below:
        # constructor arg (the server passes Config fields, which already
        # fold CLI > env > config file) > raw env var (deprecated spelling
        # for directly-constructed executors) > default.
        self._matrix_cache_entries = matrix_cache_entries or int(
            os.environ.get("PILOSA_TPU_MATRIX_CACHE_ENTRIES", "4")  # analysis-ok: env-knob-outside-config: deprecated spelling for directly-constructed executors
        )
        self._matrix_rows_max = matrix_rows_max or int(
            os.environ.get("PILOSA_TPU_MATRIX_ROWS_MAX", "1024")  # analysis-ok: env-knob-outside-config: deprecated spelling for directly-constructed executors
        )
        # Group-commit micro-batching for singleton SetBit requests (the
        # server enables this; see pilosa_tpu/ingest.py), and read
        # COALESCING for concurrent flat-lane count requests: under
        # thread contention the rotating leader concatenates many
        # requests' pair arrays into ONE vectorized evaluation (one
        # native gram-lane call for the union), instead of N threads
        # fighting over the interpreter per request.
        self._write_queue = None
        self._serve_queue = None
        # Per-THREAD armed tables for the write lanes (the table-per-
        # thread registry extending PR-10's armed-table validity rule):
        # each serving thread owns a private {(index, frame) -> arm}
        # pair — (idx_obj, frame_obj) tuples for the singleton regex
        # lane, armed request dicts for the native write lane — so
        # concurrent writers neither share nor lock one table.  Every
        # entry is still identity-revalidated per request (frame
        # deletion/recreation yields new objects; the per-fragment
        # container table's own validity lives in Fragment._writelane),
        # so a stale entry is never wrong, just a wasted probe; the
        # epoch below exists to release dead index/frame objects
        # promptly on explicit drops.
        self._lane_local = threading.local()
        self._lane_epoch = 0
        self._writelane_env: Optional[bool] = None  # lazy env-gate read
        self._fastwrite_env: Optional[bool] = None  # lazy env-gate read
        # Cached serve states for the single-call native read lane
        # (_flat_fast_path), keyed (index, frame) in a small LRU so a
        # workload alternating between a few frames' dashboards doesn't
        # thrash one slot.  Each entry is captured when a warm Gram
        # answers a single-frame flat batch, revalidated per request by
        # fragment generations + max_slice, dropped on any mismatch.
        self._serve_states: "OrderedDict[tuple[str, str], dict]" = OrderedDict()
        # LRU capacity: constructor arg (server passes Config.serve_state_cache)
        # > PILOSA_SERVE_STATE_CACHE env > default 4 entries.  One entry per
        # (index, frame) dashboard; size for the number of frames a workload
        # alternates between.
        if serve_state_cache <= 0:
            serve_state_cache = int(os.environ.get("PILOSA_SERVE_STATE_CACHE", "4"))
        self._serve_states_max = max(1, serve_state_cache)
        # Warm-state repair budget: a write burst touching at most this many
        # distinct rows gets the PATCH lane (in-place matrix row rewrite +
        # rank-k Gram repair); bigger deltas fall back to the full
        # invalidate-and-rebuild.  0 disables repair entirely (A/B lever;
        # bench_mixed uses it for the rebuild baseline).  Precedence
        # matches serve_state_cache: constructor arg (server passes
        # Config.repair_rows_max) > PILOSA_TPU_REPAIR_ROWS_MAX env >
        # default 64 (None = not configured; 0 is meaningful).
        if repair_rows_max is None:
            # analysis-ok: env-knob-outside-config: deprecated spelling for directly-constructed executors
            repair_rows_max = int(os.environ.get("PILOSA_TPU_REPAIR_ROWS_MAX", "64"))
        self._repair_rows_max = repair_rows_max
        # Gram row ceiling override (same precedence; 0 = env/default,
        # resolved lazily in _gram_env alongside the NO_GRAM switch).
        self._gram_rows_max_cfg = gram_rows_max
        # Routed strategy knobs (ctor > env > default; None/0 = fall
        # through to the deprecated env spelling).
        self._no_gram_cfg = no_gram
        self._stream_bytes_cfg = int(stream_bytes)
        self._slice_chunk_cfg = int(slice_chunk)
        # Cost-based strategy planner (planner.Planner) and background
        # pre-armer (planner.PreArmer).  The executor never CONSULTS the
        # planner — plans arrive on ExecOptions.plan from the front door
        # — it only folds outcomes back (record) and signals the
        # pre-armer from its serve/invalidate seams.  None (the default
        # everywhere but the configured server) keeps each seam one
        # branch, the same contract as the meter and tracing.
        self.planner = None
        self.prearmer = None
        # Per-(index, frame) dirty-row ledger fed by the write paths: the
        # serve-state patch lane's cheap budget precheck (the exact
        # generation-anchored delta comes from the fragment dirty-row
        # journals, which also cover non-executor writers).  Value None =
        # saturated (a burst blew past the budget; rebuild, don't walk
        # journals).
        self._dirty_rows: dict[tuple[str, str], Optional[set]] = {}
        self._dirty_mu = lockcheck.named_lock("executor._dirty_mu")
        self._gram_env_cache: Optional[tuple[bool, int]] = None  # lazy env read
        # Generation-keyed query result cache (qcache.QueryCache), the
        # whole-query memoization layer in front of every read path.
        # Default sentinel "env" = enabled only when PILOSA_TPU_QCACHE is
        # truthy, so directly-constructed executors (tests, benches,
        # embedders) keep pre-qcache behavior; the server and lockstep
        # service pass a configured instance (or None = disabled).
        if qcache == "env":
            qcache = qcache_mod.from_env()
        self.qcache = qcache
        # Device-side cost attribution (costs.DispatchMeter): the engine
        # dispatch seams — gram / gather / stream / native — emit
        # per-dispatch wall time + transfer bytes as tagged histograms
        # and, for traced requests, "device" child spans.  None (the
        # default for directly-constructed executors) keeps every seam a
        # single ``meter is None`` branch, the same contract as tracing.
        self.meter = None
        if stats is not None:
            from pilosa_tpu import costs as costs_mod

            self.meter = costs_mod.DispatchMeter(stats, engine=self.engine)
        if write_queue:
            from pilosa_tpu.ingest import WriteQueue

            self._write_queue = WriteQueue(self._apply_queued_writes)
            self._serve_queue = WriteQueue(self._apply_queued_reads, max_batch=64)

    def _lane_tables(self):
        """This thread's private armed write-lane tables:
        ``(fastwrite, writelane)`` dicts keyed (index, frame).

        Thread-private, so no lock and no cross-thread mutation; a
        drop_frame_state/drop_index_state bumps ``_lane_epoch`` and
        every thread discards its own tables at next access.  A thread
        racing the bump may finish one more request on a stale entry —
        harmless, because both lanes revalidate index/frame object
        identity (and the fragment container table its generation)
        before every use.
        """
        loc = self._lane_local
        epoch = self._lane_epoch
        if getattr(loc, "epoch", None) != epoch:
            loc.epoch = epoch
            loc.fastwrite = {}
            loc.writelane = {}
        return loc.fastwrite, loc.writelane

    # -- top level (executor.go:65-153) ----------------------------------

    def execute(
        self,
        index: str,
        query,
        slices: Optional[Sequence[int]] = None,
        opt: Optional[ExecOptions] = None,
    ) -> list[Any]:
        if opt is not None and opt.deadline is not None:
            # Door checkpoint: an already-expired request never touches
            # the serve lane (fast paths included).
            opt.deadline.check("pre-execution")
        # Request trace span (None = unsampled: every site below is one
        # branch).  Tags record the cache disposition and which strategy
        # lane answered; child spans time the stages.
        span = opt.span if opt is not None else None
        qtoken = None
        if isinstance(query, str):
            # Query result cache: a valid generation-keyed entry answers
            # the whole request here — no parse, no dispatch, no device
            # work.  A cacheable miss carries a _Pending token through
            # execution; the read return paths below commit it (errors
            # propagate past the commit, so they are never cached).
            if self.qcache is not None:
                remote = bool(opt is not None and opt.remote)
                if opt is not None and opt.no_cache:
                    self.qcache.note_bypass()
                    if span is not None:
                        span.tags["qcache"] = "bypass"
                elif query[:64].lstrip()[:9].startswith(("SetBit(", "ClearBit(")):
                    # Cheap write sniff: a body whose first call mutates
                    # is write-bearing and can never be cached — skip
                    # the eligibility probe's memoized parse so the
                    # write lanes never pay it (every write body is a
                    # distinct string, so the memo never hits for them).
                    self.qcache.note_ineligible()
                    if span is not None:
                        span.tags["qcache"] = "ineligible"
                elif self.cluster is not None and not remote:
                    # Multi-node coordinator scope: the answer covers
                    # remotely-owned slices, but cluster writes apply
                    # only on owner nodes — the LOCAL generation vector
                    # can never see them, so such an entry would serve
                    # stale reads forever.  Remote sub-requests (explicit
                    # locally-owned slices, whose writes always land
                    # locally on every owner) stay cacheable.
                    self.qcache.note_ineligible()
                    if span is not None:
                        span.tags["qcache"] = "ineligible"
                else:
                    # Order-insensitive slice-set key; an explicit empty
                    # list stays distinct from None (= all slices).
                    skey = None if slices is None else tuple(sorted(slices))
                    qsp = span.child("qcache.lookup") if span is not None else None
                    cached, qtoken = self.qcache.lookup(
                        self.holder, index, query, skey, remote=remote,
                    )
                    if qsp is not None:
                        qsp.finish()
                        # qtoken None without a hit = the lookup judged
                        # the query ineligible (write-bearing tree, ...).
                        span.tags["qcache"] = (
                            "hit" if cached is not None
                            else "miss" if qtoken is not None
                            else "ineligible"
                        )
                    if cached is not None:
                        return cached
            # Singleton lane first: for n=1 the regex + fused
            # pn_array_add_logged path is already one crossing and
            # beats pn_write_batch's 22-arg marshalling; the native
            # batch lane owns everything the singleton shape declines
            # (multi-call bodies, ClearBit batches, NO_FASTWRITE A/B).
            w = self._singleton_write_fast(index, query, slices, opt)
            if w is not None:
                if span is not None:
                    span.tags["lane"] = "write_fast"
                return w
            w = self._write_fast_lane(index, query, slices, opt)
            if w is not None:
                if span is not None:
                    span.tags["lane"] = "write_native"
                return w
            fast = self._flat_fast_path(index, query, slices, opt)
            if fast is not None:
                if span is not None:
                    # The compiled-query lane answered (native serve /
                    # Gram / gather kernels behind one entry point).
                    span.tags["lane"] = "flat"
                if qtoken is not None:
                    self.qcache.commit(self.holder, qtoken, fast)
                return fast
            psp = span.child("parse") if span is not None else None
            query = pql.parse_cached(query)
            if psp is not None:
                psp.finish()
        if not query.calls:
            raise ErrQueryRequired("query required")
        if self.max_writes_per_request and query.write_call_n() > self.max_writes_per_request:
            raise ErrTooManyWrites(
                f"too many write commands: {query.write_call_n()} > {self.max_writes_per_request}"
            )
        idx = self.holder.index(index)
        if idx is None:
            raise ErrIndexNotFound(index)
        opt = opt or ExecOptions()

        std_slices = list(slices) if slices else None
        inv_slices = None
        if std_slices is None and needs_slices(query.calls):
            std_slices = list(range(idx.max_slice() + 1))
            inv_slices = list(range(idx.max_inverse_slice() + 1))

        if (
            self._write_queue is not None
            and not opt.remote
            and len(query.calls) == 1
            and query.calls[0].name == "SetBit"
        ):
            # Singleton SetBit: group-commit through the ingest queue.
            # Args are parsed HERE (one client's malformed call raises on
            # its own request, never poisoning a shared batch) and the
            # parsed tuple rides along so the committer doesn't re-parse.
            try:
                parsed = self._set_bit_args(index, query.calls[0])
            except (PilosaError, ValueError):
                pass  # sequential path surfaces the exact error
            else:
                return [self._write_queue.submit((index, query.calls[0], parsed))]

        batched_writes = self._fuse_set_bit_batch(index, query.calls, opt)
        if batched_writes is not None:
            return batched_writes

        fsp = span.child("fused") if span is not None else None
        fused = self._fuse_count_pair_batch(index, query.calls, std_slices, inv_slices, opt)
        if fused is None:
            fused = self._fuse_count_range_batch(index, query.calls, std_slices, opt)
        if fsp is not None:
            fsp.finish()
            if fused is None:
                # No fused group matched: the span only measured the
                # (cheap) match attempt — drop it from the tree.
                span.children.remove(fsp)
            else:
                fsp.tags["calls"] = len(fused)
                fsp.tags["slices"] = len(std_slices or [])
                span.tags["lane"] = "fused"

        results = []
        for i, call in enumerate(query.calls):
            if opt.deadline is not None and i:
                # Cancellation checkpoint between calls: an expired
                # request stops here instead of finishing the batch.
                opt.deadline.check("between calls")
            if fused is not None and i in fused:
                results.append(fused[i])
                continue
            csp = span.child(f"call.{call.name}") if span is not None else None
            call_slices = std_slices
            if call.supports_inverse() and std_slices is not None and inv_slices is not None:
                frame_name = call.string_arg("frame") or DEFAULT_FRAME
                frame = self.holder.frame(index, frame_name)
                if frame is None:
                    raise ErrFrameNotFound(frame_name)
                if call.is_inverse(frame.row_label, idx.column_label):
                    call_slices = inv_slices
            # The call's fan-out/remote spans nest under the call span
            # (shallow option copy — opt itself is shared state).
            call_opt = opt if csp is None else dc_replace(opt, span=csp)
            results.append(self._execute_call(index, call, call_slices, call_opt))
            if csp is not None:
                csp.finish()
        if qtoken is not None:
            self.qcache.commit(self.holder, qtoken, results)
        return results

    # -- query-batch fusion ------------------------------------------------

    def _fuse_set_bit_batch(
        self, index: str, calls, opt: ExecOptions
    ) -> Optional[list[bool]]:
        """Batch an all-SetBit request into vectorized per-frame writes.

        The write-path analog of the count-intersect fusion: a request
        carrying N SetBit calls costs one fragment pass + one WAL append
        per touched (view, slice) — and one forwarded request per remote
        owner node — instead of N of each (executor.go:675-698 does N).
        Only fires when the WHOLE request is SetBit calls, so per-call
        ordering against reads is preserved; per-call changed bools are
        identical to the sequential path (first duplicate wins).

        Failure semantics differ from sequential on purpose: local writes
        are all applied first, then remote forwards — so a node failure
        leaves every locally-owned bit committed (sequential leaves a
        call-order prefix).  SetBit is idempotent, so a client retry
        converges to the same state on either path.
        """
        if len(calls) < 2 or any(c.name != "SetBit" for c in calls):
            return None
        try:
            parsed = [self._set_bit_args(index, c) for c in calls]
        except (PilosaError, ValueError):
            # Surface the error through the sequential path, which also
            # preserves its partial-commit semantics (calls before the bad
            # one take effect, exactly as if executed one by one).
            return None
        return self._commit_set_bits(index, calls, parsed, opt)

    def _commit_set_bits(self, index: str, calls, parsed, opt: ExecOptions) -> list[bool]:
        """Apply pre-parsed SetBit tuples: vectorized local writes + one
        forwarded request per remote owner node (shared by the fused
        batch path and the ingest queue's committer)."""
        changed = [False] * len(calls)

        # Ownership split: local writes for slices this node owns, one
        # batched forward per remote owner node.
        by_node: dict[str, list[int]] = {}
        if opt.remote or self.cluster is None or self.client_factory is None:
            local_idx = list(range(len(calls)))
        else:
            local_idx = []
            for i, (_, _, col_id, _) in enumerate(parsed):
                for node in self.cluster.fragment_nodes(index, col_id // SLICE_WIDTH):
                    if node.host == self.host:
                        local_idx.append(i)
                    else:
                        by_node.setdefault(node.host, []).append(i)

        by_frame: dict[Any, list[int]] = {}
        for i in local_idx:
            by_frame.setdefault(parsed[i][0], []).append(i)
        for frame, idxs in by_frame.items():
            rows = np.array([parsed[i][1] for i in idxs], dtype=np.uint64)
            cols = np.array([parsed[i][2] for i in idxs], dtype=np.uint64)
            stamps = [parsed[i][3] for i in idxs]
            ch = frame.set_bits(VIEW_STANDARD, rows, cols, stamps)
            if ch.any():
                self._note_dirty_rows(index, frame.name, rows[ch].tolist())
            if frame.inverse_enabled:
                ch |= frame.set_bits(VIEW_INVERSE, cols, rows, stamps)
            for k, i in enumerate(idxs):
                if ch[k]:
                    changed[i] = True

        for host, idxs in by_node.items():
            client = self.client_factory(host)
            q = pql.Query(calls=[calls[i] for i in idxs])
            res = client.execute_remote(index, q, deadline=opt.deadline)
            for k, i in enumerate(idxs):
                if res and res[k]:
                    changed[i] = True
        return changed

    def _apply_queued_writes(self, items) -> list:
        """Commit one drained queue batch: [(index, call, parsed)] ->
        per-item changed bools, via the fused vectorized write path (one
        fragment pass + one WAL append per touched view/slice, cluster
        forwarding included).  Uses the parse results captured at submit;
        a frame deleted/recreated in between is caught by ONE re-resolve
        per (index, frame) group and that item re-parsed (an error becomes
        that item's result only — never the batch's)."""
        by_index: dict[str, list[int]] = {}
        for i, (idx_name, _, _) in enumerate(items):
            by_index.setdefault(idx_name, []).append(i)
        results: list = [None] * len(items)
        opt = ExecOptions()
        for idx_name, positions in by_index.items():
            calls = [items[i][1] for i in positions]
            parsed = [items[i][2] for i in positions]
            live = {}
            for k, p in enumerate(parsed):
                fr = p[0]
                ok = live.get(id(fr))
                if ok is None:
                    ok = live[id(fr)] = (
                        self.holder.frame(idx_name, fr.name) is fr
                    )
                if not ok:
                    try:  # stale frame object: re-parse against the holder
                        parsed[k] = self._set_bit_args(idx_name, calls[k])
                    except (PilosaError, ValueError) as e:
                        parsed[k] = e
            ok_pos = [k for k, p in enumerate(parsed) if not isinstance(p, BaseException)]
            for k, p in enumerate(parsed):
                if isinstance(p, BaseException):
                    results[positions[k]] = p  # raised on that submitter only
            if ok_pos:
                res = self._commit_set_bits(
                    idx_name,
                    [calls[k] for k in ok_pos],
                    [parsed[k] for k in ok_pos],
                    opt,
                )
                for j, k in enumerate(ok_pos):
                    results[positions[k]] = res[j]
        return results

    # PQL pair-op -> kernel op for the fused batch path.
    _FUSABLE_OPS = {
        "Intersect": "and",
        "Union": "or",
        "Difference": "andnot",
        "Xor": "xor",
    }
    # The canonical singleton-write shape clients emit (and the reference
    # bench tool generates, ctl/bench.go:71-102): ONE SetBit/ClearBit with
    # positional-canonical args and no timestamp.  Anything else declines
    # to the general path.
    _SINGLETON_WRITE_RX = re.compile(
        r'^\s*(SetBit|ClearBit)\(\s*([A-Za-z_][A-Za-z0-9_]*)\s*=\s*(\d+)\s*,'
        r'\s*frame\s*=\s*"([a-z][a-z0-9_-]{0,64})"\s*,'
        r'\s*([A-Za-z_][A-Za-z0-9_]*)\s*=\s*(\d+)\s*\)\s*$'
    )

    # First frame= reference in a canonical write body (quoted or bare).
    _WRITE_FRAME_SNIFF_RX = re.compile(
        r'frame\s*=\s*(?:"([^"\\]*)"|\'([^\'\\]*)\'|([A-Za-z][A-Za-z0-9._-]*))'
    )

    def _write_fast_lane(self, index: str, src: str, slices, opt) -> Optional[list]:
        """Native write request lane: a canonical all-SetBit/ClearBit
        request body — singleton or batch — runs parse + sorted
        container inserts + WAL group commit in ONE GIL-released
        ``pn_write_batch`` crossing against the armed fragment
        (Fragment.write_batch), the write-side twin of the
        ``pn_serve_pairs`` read lane.  A structurally-declined batch
        still reuses the native PARSE: the ops apply through the
        vectorized Python batch path without ever touching the Python
        tokenizer.  Returns None for anything outside the exact shape —
        clusters, explicit slices, inverse frames, multi-slice frames,
        non-canonical bodies — so the general lane keeps every behavior
        and error message (it is also the differential-test oracle:
        both lanes must produce identical fragment bytes, WAL frames,
        and changed vectors).
        """
        if self.cluster is not None or slices:
            return None
        no_lane = self._writelane_env
        if no_lane is None:
            # Read once per executor (~2 us/op otherwise); tests that
            # toggle the env construct a fresh Executor (or reset
            # _writelane_env to None).
            # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
            no_lane = self._writelane_env = os.environ.get(
                "PILOSA_TPU_NO_WRITELANE", ""
            ).lower() in ("1", "true", "yes")
        if no_lane:
            return None
        head = src[:64].lstrip()[:9]
        if not head.startswith(("SetBit(", "ClearBit(")):
            return None
        if native_mod.load() is None:
            return None
        if self.max_writes_per_request:
            # Exact per canonical shape (one "Bit(" per call); checked
            # BEFORE any mutation so the over-limit error keeps the
            # general path's raise-before-write semantics.
            if src.count("Bit(") > self.max_writes_per_request:
                return None  # general path raises ErrTooManyWrites
        m = self._WRITE_FRAME_SNIFF_RX.search(src, 0, 256)
        if m is None:
            return None
        fname = m.group(1) or m.group(2) or m.group(3)
        _, writelane = self._lane_tables()  # this thread's private table
        st = writelane.get((index, fname))
        if st is None or self.holder.index(index) is not st["idx_obj"]:
            writelane.pop((index, fname), None)
            idx_obj = self.holder.index(index)
            if idx_obj is None:
                return None  # general path raises in canonical order
            frame = idx_obj.frame(fname)
            if frame is None:
                return None
            try:
                st = {
                    "idx_obj": idx_obj,
                    "frame": frame,
                    "frame_b": fname.encode("utf-8"),
                    "rowkey_b": frame.row_label.encode("utf-8"),
                    "colkey_b": idx_obj.column_label.encode("utf-8"),
                    "frag": None,
                }
            except UnicodeEncodeError:
                return None
            writelane[(index, fname)] = st
        idx_obj, frame = st["idx_obj"], st["frame"]
        if idx_obj.frame(fname) is not frame:
            writelane.pop((index, fname), None)
            return None
        if frame.inverse_enabled:
            return None  # dual-view writes: general path
        view = frame.view(VIEW_STANDARD)
        frags = view.fragments if view is not None else {}
        frag = st["frag"]
        if frag is None or frags.get(frag.slice) is not frag:
            # Arm the fragment: the lane serves the canonical single-
            # slice shape (one standard-view fragment); multi-slice
            # frames take the general path.
            if len(frags) != 1:
                st["frag"] = None
                return None
            frag = next(iter(frags.values()))
            st["frag"] = frag
        try:
            raw = src.encode("utf-8")
        except UnicodeEncodeError:
            return None
        if self.meter is not None:
            span = opt.span if opt is not None else None
            with self.meter.measure("native", span) as d:
                res = frag.write_batch(
                    raw, st["frame_b"], st["rowkey_b"], st["colkey_b"]
                )
                d.add_bytes(len(raw))
        else:
            res = frag.write_batch(
                raw, st["frame_b"], st["rowkey_b"], st["colkey_b"]
            )
        if res is None:
            return None
        changed, types, rows, cols = res
        if changed is not None:
            if len(changed) == 1:  # singleton hot path: no numpy work
                ch = bool(changed[0])
                if ch:
                    self._note_dirty_rows(index, fname, (int(rows[0]),))
                return [ch]
            if changed.any():
                self._note_dirty_rows(
                    index, fname, np.unique(rows[changed]).tolist()
                )
            return changed.tolist()
        # Parsed-only: apply through the vectorized Python batch path
        # (sequential scalar path for mixed set/clear bodies, whose
        # in-batch ordering matters).
        if (types == 0).all():
            ch = frame.set_bits(VIEW_STANDARD, rows, cols)
            if ch.any():
                self._note_dirty_rows(index, fname, rows[ch].tolist())
            return ch.tolist()
        out: list[bool] = []
        touched: list[int] = []
        for t, r, c in zip(types.tolist(), rows.tolist(), cols.tolist()):
            if t == 0:
                ok = frame.set_bit(VIEW_STANDARD, r, c)
            else:
                ok = frame.clear_bit(VIEW_STANDARD, r, c)
            if ok:
                touched.append(r)
            out.append(ok)
        if touched:
            self._note_dirty_rows(index, fname, touched)
        return out

    def _singleton_write_fast(self, index: str, src: str, slices, opt) -> Optional[list]:
        """Durable singleton SetBit/ClearBit with minimal per-request
        Python: one regex + cached (index, frame) resolution + the scalar
        frame write.  The general path costs ~10x more per op in parse +
        queue + batched-commit machinery that buys nothing for a single
        bit; under concurrent clients the GIL makes that per-op Python
        THE write-throughput ceiling (BASELINE.md round-4 waiver note).

        Declines (returns None) for anything beyond the simple local
        shape: clusters (owner forwarding), inverse-enabled frames (dual
        writes), non-canonical arg names/order, timestamps, remote opts.
        """
        if self.cluster is not None or slices:
            return None
        no_fast = self._fastwrite_env
        if no_fast is None:
            # A/B lever (BENCH_CONFIG=writelane): disable the regex
            # singleton lane so singletons flow to the native batch
            # lane / general path.  Read once per executor.
            # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
            no_fast = self._fastwrite_env = os.environ.get(
                "PILOSA_TPU_NO_FASTWRITE", ""
            ).lower() in ("1", "true", "yes")
        if no_fast:
            return None
        m = self._SINGLETON_WRITE_RX.match(src)
        if m is None:
            return None
        name, k1, v1, fname, k2, v2 = m.groups()
        fastwrite, _ = self._lane_tables()  # this thread's private table
        cached = fastwrite.get((index, fname))
        if cached is None or self.holder.index(index) is not cached[0]:
            fastwrite.pop((index, fname), None)  # no dead pins
            idx_obj = self.holder.index(index)
            if idx_obj is None:
                return None  # general path raises in canonical order
            frame = idx_obj.frame(fname)
            if frame is None:
                return None
            cached = (idx_obj, frame)
            fastwrite[(index, fname)] = cached
        idx_obj, frame = cached
        if idx_obj.frame(fname) is not frame:
            fastwrite.pop((index, fname), None)
            return None
        if (
            frame.inverse_enabled
            or k1 != frame.row_label
            or k2 != idx_obj.column_label
        ):
            return None
        row_id, col_id = int(v1), int(v2)
        if name == "SetBit":
            ch = frame.set_bit(VIEW_STANDARD, row_id, col_id)
        else:
            ch = frame.clear_bit(VIEW_STANDARD, row_id, col_id)
        if ch:
            self._note_dirty_rows(index, fname, (row_id,))
        return [ch]

    def _flat_fast_path(self, index: str, src: str, slices, opt) -> Optional[list]:
        """Compiled-query lane: serve an all-``Count(<op>(Bitmap,Bitmap))``
        request straight from the native matcher's pair arrays — no Token
        stream, no Call objects, no per-call Python work (the dominant
        host costs of a large batched request).  Returns None for
        ANYTHING outside the exact shape — other calls, inverse views,
        unusual args, parse errors — so the normal parse path keeps every
        behavior and error message.
        """
        # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
        if os.environ.get("PILOSA_TPU_NO_FASTLANE", "").lower() in ("1", "true", "yes"):
            return None
        from pilosa_tpu import native

        try:
            raw = src.encode("utf-8")
        except UnicodeEncodeError:
            return None
        opt = opt or ExecOptions()
        local = slices is None and not self._is_distributed(opt)
        # Planner plan, applied at every exit of this lane: the armed
        # native serve path IS the gram strategy family, so a forced
        # "rmgather" plan must skip it (or the alternate lane could
        # never run once a state arms) and every native answer folds
        # back under lane "gram" — steady-state costs keep flowing into
        # the ledger after arming, not just the cold passes.  A lane of
        # None (static/empty ledger) leaves every branch below exactly
        # as it was — the static-parity contract.
        plan = opt.plan
        forced = plan.get("lane") if plan is not None else None
        rec = self.planner is not None and plan is not None
        # Single-call serving lane: with a valid cached serve state the
        # WHOLE request — parse, frame/row-label validation, Gram count
        # identities — runs inside one GIL-released native call
        # (pn_serve_pairs), the steady-state product loop with no
        # per-request Python beyond the validity token check
        # (server.go:150 + executor.go:1209-1244's concurrent serving,
        # compiled).  Concurrent clients call it directly — the native
        # call holds no Python state, so threads overlap inside it
        # (measured: a spinner thread retains full throughput during the
        # call; sustained 16-thread load shows no inversion) — and any
        # decline falls through to the general lane, which refreshes the
        # state.  The serve QUEUE below only coalesces the cold/unarmed
        # path, where per-request Python still dominates.
        if local and self._serve_states and forced != "rmgather":
            # Pick the candidate state by SNIFFING the first frame
            # reference (cheap regex over the request head) instead of
            # trying every armed state — each native attempt re-parses
            # the whole batch, so a decline ladder would tax alternating
            # multi-frame dashboards with a full wasted parse per
            # request.  A servable request is single-frame anyway (the C
            # validator enforces it), so the first reference decides.
            sn = _FRAME_SNIFF_RX.search(src, 0, 512)
            fname = sn.group(1) or sn.group(2) or sn.group(3) if sn else DEFAULT_FRAME
            st = self._serve_states.get((index, fname))
            if st is not None and not self._serve_state_valid(st):
                # Patch lane: a small write repairs the warm state in
                # place (matrix rows + rank-k Gram + glut) and re-arms;
                # only structural or over-budget deltas pop the entry
                # and pay the full rebuild through the general lane.
                st = self._serve_state_repair((index, fname), st)
                if st is None:
                    with self._matrix_mu:
                        self._serve_states.pop((index, fname), None)
            if st is not None:
                t0 = time.perf_counter() if rec else 0.0
                if self.meter is not None:
                    with self.meter.measure("native", opt.span) as d:
                        counts = native.serve_pairs(
                            raw, st["frame_b"], st["allow_default"],
                            st["rowkey_b"], st["rs"], st["ps"], st["gram"],
                        )
                        d.add_bytes(len(raw))
                else:
                    counts = native.serve_pairs(
                        raw, st["frame_b"], st["allow_default"], st["rowkey_b"],
                        st["rs"], st["ps"], st["gram"],
                    )
                if counts is not None:
                    if opt.span is not None:
                        # Frame attribution for the cost ledger: the
                        # serve lane is single-frame by construction.
                        opt.span.tags["frame"] = fname
                    # Guard: a concurrent invalidation/eviction during
                    # the GIL-released call may have removed the key.
                    # LRU maintenance under _matrix_mu like every other
                    # serve-state mutation (guarded-fields declaration);
                    # the native call above runs outside any lock.
                    with self._matrix_mu:
                        if (index, fname) in self._serve_states:
                            self._serve_states.move_to_end((index, fname))
                    if rec:
                        self.planner.record(
                            index=index, fp=plan.get("fp", ""), lane="gram",
                            ms=(time.perf_counter() - t0) * 1e3, plan=plan,
                        )
                    return counts.tolist()
            # Multi-frame breadth: a batch spanning SEVERAL armed frames
            # (the single-state path above only ever serves one) still
            # answers in one crossing — pn_serve_multi evaluates each
            # call against its frame's glut.  Also covers the case where
            # the sniffed frame's state was just invalidated but the
            # batch's other frames are warm: the native validator simply
            # declines on the missing frame and the general lane re-arms.
            # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
            if len(self._serve_states) > 1 and os.environ.get(
                "PILOSA_TPU_NO_SERVEMULTI", ""
            ).lower() not in ("1", "true", "yes"):
                t0 = time.perf_counter() if rec else 0.0
                counts = self._serve_multi_counts(index, raw, opt)
                if counts is not None:
                    if rec:
                        self.planner.record(
                            index=index, fp=plan.get("fp", ""), lane="gram",
                            ms=(time.perf_counter() - t0) * 1e3, plan=plan,
                        )
                    return counts
        m = native.pql_match_pairs(raw)
        if m is None:
            # Not an all-pairs body: the breadth lanes own the other
            # compiled shapes before the tokenizer runs — nested op
            # trees straight off the armed container table, then
            # all-Count(Range(...)) batches through the fused multi-view
            # evaluator with the parse already native.
            if local:
                tree = self._tree_fast_path(index, raw, src, opt)
                if tree is not None:
                    return tree
                return self._range_fast_path(index, raw, opt)
            return None
        op_ids, frame_ids, key_ids, r1, r2, frames_b, keys_b = m

        # Validate each distinct (frame, row-key) combo once: the key must
        # be the frame's row label (standard view; inverse and unknown
        # labels take the slow path, missing frames raise there too).
        frame_names = [b.decode("utf-8") for b in frames_b]
        key_names = [b.decode("utf-8") for b in keys_b]
        for f_id, k_id in sorted(set(zip(frame_ids.tolist(), key_ids.tolist()))):
            fname = frame_names[f_id] if f_id >= 0 else DEFAULT_FRAME
            fr = self.holder.frame(index, fname)
            if fr is None or key_names[k_id] != fr.row_label:
                return None
        # Index resolution AFTER shape matching keeps error precedence
        # identical to the normal path (shape mismatches never raise here).
        idx_obj = self.holder.index(index)
        if idx_obj is None:
            return None  # normal path raises ErrIndexNotFound in order
        std_slices = list(slices) if slices else list(range(idx_obj.max_slice() + 1))
        if not std_slices:
            return None
        if (
            pool_capacity(len(std_slices), _WORDS) < 64
            or len(std_slices) > _INT32_SAFE_SLICES
        ):
            # Slice-streaming regime (working set >> HBM pool budget) or a
            # slice span past the kernels' int32 count bound: the AST
            # fused path owns the slice-chunked accumulation loop; the
            # flat lane's whole point (skipping per-call Python) is noise
            # against per-chunk upload costs anyway.
            return None

        # A plan with a FORCED lane bypasses the coalescing queue: the
        # queue's fused evaluation is shared across requests (so it runs
        # planless, like the lockstep multi-request join), and a
        # planner-made pick must actually run — and fold back — on its
        # own lane.  Static plans (lane None) keep the queue, bit-exact.
        if self._serve_queue is not None and local and forced is None:
            # Read coalescing: hand the matched arrays to the serve queue;
            # the current leader concatenates every queued request with
            # the same (index, name tables, slice set) into one vectorized
            # evaluation.  Uncontended, the batch is just this request.
            return self._serve_queue.submit(
                (
                    index,
                    (op_ids, frame_ids, r1, r2),
                    (tuple(frames_b), tuple(keys_b)),
                    tuple(std_slices),
                )
            )
        if self._is_distributed(opt):
            # Cluster hop: build the matched dict + forwarded Query (from
            # the parse cache) and reuse the failover machinery.
            matched = {
                i: (
                    frame_names[frame_ids[i]] if frame_ids[i] >= 0 else DEFAULT_FRAME,
                    VIEW_STANDARD,
                    native.PQL_PAIR_OPS[op_ids[i]],
                    (int(r1[i]), int(r2[i])),
                )
                for i in range(len(op_ids))
            }
            idxs = list(range(len(op_ids)))
            return self._fused_dispatch(
                index, idxs, std_slices, opt,
                lambda: pql.parse_cached(src),
                lambda node_slices: self._fused_local_counts(
                    index, matched, idxs, node_slices, plan=opt.plan
                ),
            )
        return self._fused_local_counts_arrays(
            index, frame_names, op_ids, frame_ids, r1, r2, std_slices,
            plan=opt.plan,
        )

    def _serve_state_valid(self, st: dict) -> bool:
        """Cheap per-request token check for the cached serve state:
        index identity, unchanged max slice, and per-slice fragment
        identity + write generation (creation, recreation, and every
        write bump a token)."""
        idx_obj = st["idx_obj"]
        if self.holder.index(st["index"]) is not idx_obj:
            return False
        if idx_obj.max_slice() != st["max_slice"]:
            return False
        index, fname = st["index"], st["fname"]
        for s, frag, gen in st["slots"]:
            f = self.holder.fragment(index, fname, VIEW_STANDARD, s)
            if f is not frag or (f is not None and f.generation != gen):
                return False
        return True

    # -- serve-lane breadth (multi-frame / Range / nested-tree) -----------

    def _serve_multi_counts(self, index: str, raw: bytes, opt) -> Optional[list]:
        """Multi-frame one-call serving: bundle every VALID armed state
        for the index (names, row labels, glut base addresses) and hand
        the whole request to ``pn_serve_multi`` — parse, per-frame
        validation, and Gram count identities in one GIL-released
        crossing.  Any decline (unknown frame, cold frame, unknown row)
        returns None and the general lane re-arms per frame.
        """
        from pilosa_tpu import native

        with self._matrix_mu:
            cands = [st for k, st in self._serve_states.items() if k[0] == index]
        states = [st for st in cands if self._serve_state_valid(st)][:16]
        if len(states) < 2:
            return None
        name_offs = np.zeros(len(states) + 1, dtype=np.int64)
        rlabel_offs = np.zeros(len(states) + 1, dtype=np.int64)
        default_sid = -1
        for i, st in enumerate(states):
            name_offs[i + 1] = name_offs[i] + len(st["frame_b"])
            rlabel_offs[i + 1] = rlabel_offs[i] + len(st["rowkey_b"])
            if st["allow_default"]:
                default_sid = i
        names_cat = b"".join(st["frame_b"] for st in states)
        rlabels_cat = b"".join(st["rowkey_b"] for st in states)
        # Raw glut addresses: the `states` list keeps every array alive
        # across the call; entries evicted concurrently stay pinned here.
        rs_addrs = np.array([st["rs"].ctypes.data for st in states], dtype=np.uint64)
        ps_addrs = np.array([st["ps"].ctypes.data for st in states], dtype=np.uint64)
        gram_addrs = np.array(
            [st["gram"].ctypes.data for st in states], dtype=np.uint64
        )
        n_rows = np.array([len(st["rs"]) for st in states], dtype=np.int64)
        gram_dims = np.array([st["gram"].shape[0] for st in states], dtype=np.int64)
        if self.meter is not None:
            with self.meter.measure("native", opt.span) as d:
                counts = native.serve_multi(
                    raw, names_cat, name_offs, rlabels_cat, rlabel_offs,
                    default_sid, rs_addrs, ps_addrs, gram_addrs, n_rows, gram_dims,
                )
                d.add_bytes(len(raw))
        else:
            counts = native.serve_multi(
                raw, names_cat, name_offs, rlabels_cat, rlabel_offs,
                default_sid, rs_addrs, ps_addrs, gram_addrs, n_rows, gram_dims,
            )
        if counts is None:
            return None
        with self._matrix_mu:
            for st in states:
                k = (index, st["fname"])
                if self._serve_states.get(k) is st:
                    self._serve_states.move_to_end(k)
        return counts.tolist()

    def _tree_fast_path(self, index: str, raw: bytes, src: str, opt) -> Optional[list]:
        """Nested-tree serving: an all-Count(op-tree over Bitmap leaves)
        body evaluated straight off the fragment's armed container table
        (``pn_serve_tree`` — matcher and evaluator fused, intermediate id
        arrays never materialize).  Single-slice local indexes only: the
        armed table is per fragment and the whole call runs under that
        fragment's lock.  None for anything outside the shape.
        """
        # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
        if os.environ.get("PILOSA_TPU_NO_SERVETREE", "").lower() in (
            "1", "true", "yes",
        ):
            return None
        idx_obj = self.holder.index(index)
        if idx_obj is None or idx_obj.max_slice() != 0:
            return None
        sn = _FRAME_SNIFF_RX.search(src, 0, 512)
        fname = sn.group(1) or sn.group(2) or sn.group(3) if sn else DEFAULT_FRAME
        fr = self.holder.frame(index, fname)
        if fr is None:
            return None
        frag = self.holder.fragment(index, fname, VIEW_STANDARD, 0)
        if frag is None:
            return None
        try:
            frame_b = fname.encode("ascii")
            rowkey_b = fr.row_label.encode("ascii")
        except UnicodeEncodeError:
            return None
        if self.meter is not None:
            with self.meter.measure("native", opt.span) as d:
                counts = frag.serve_tree(
                    raw, frame_b, fname == DEFAULT_FRAME, rowkey_b
                )
                d.add_bytes(len(raw))
        else:
            counts = frag.serve_tree(raw, frame_b, fname == DEFAULT_FRAME, rowkey_b)
        if counts is None:
            return None
        if opt.span is not None:
            opt.span.tags["frame"] = fname
        return counts.tolist()

    def _range_fast_path(self, index: str, raw: bytes, opt) -> Optional[list]:
        """Native Range cover lane: ``pn_pql_match_range`` parses an
        all-Count(Range(...)) body (rows + packed digit timestamps) so
        the batch skips the Python tokenizer and rides the existing fused
        multi-view evaluator.  Validation mirrors the AST fused path —
        any decline (unknown frame, label mismatch, calendar error,
        over-budget cover set) returns None so the sequential path keeps
        every behavior and error message.
        """
        # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
        if os.environ.get("PILOSA_TPU_NO_RANGELANE", "").lower() in (
            "1", "true", "yes",
        ):
            return None
        from pilosa_tpu import native

        m = native.pql_match_range(raw)
        if m is None:
            return None
        frame_ids, key_ids, rows, starts, ends, frames_b, keys_b = m
        frame_names = [b.decode("utf-8") for b in frames_b]
        key_names = [b.decode("utf-8") for b in keys_b]
        frames: dict[int, tuple] = {}
        for f_id, k_id in sorted(set(zip(frame_ids.tolist(), key_ids.tolist()))):
            fname = frame_names[f_id] if f_id >= 0 else DEFAULT_FRAME
            fr = self.holder.frame(index, fname)
            if fr is None or key_names[k_id] != fr.row_label:
                return None
            frames[f_id] = (fname, fr)
        idx_obj = self.holder.index(index)
        if idx_obj is None:
            return None
        std_slices = list(range(idx_obj.max_slice() + 1))
        if len(std_slices) > _INT32_SAFE_SLICES:
            return None
        matched: dict[int, tuple[str, int, list[str]]] = {}
        for i in range(len(rows)):
            fname, fr = frames[int(frame_ids[i])]
            s, e = int(starts[i]), int(ends[i])
            try:
                # Packed digits -> datetime: calendar validation happens
                # HERE, so an invalid date declines to the Python parser
                # and surfaces its exact error.
                start = datetime(
                    s // 10**8, s // 10**6 % 100, s // 10**4 % 100,
                    s // 100 % 100, s % 100,
                )
                end = datetime(
                    e // 10**8, e // 10**6 % 100, e // 10**4 % 100,
                    e // 100 % 100, e % 100,
                )
            except ValueError:
                return None
            views = (
                tq.views_by_time_range(VIEW_STANDARD, start, end, fr.time_quantum)
                if fr.time_quantum
                else []
            )
            matched[i] = (fname, int(rows[i]), views)
        combos = {(f, v, r) for f, r, views in matched.values() for v in views}
        if len(combos) > self._matrix_rows_max:
            return None
        idxs = list(range(len(rows)))
        return self._fused_local_range_counts(index, matched, idxs, std_slices)

    # -- warm-state repair (delta patch instead of invalidate) ------------

    def _note_dirty_rows(self, index: str, fname: str, rows) -> None:
        """Accumulate the per-(index, frame) dirty-row ledger feeding the
        serve-state patch lane's budget precheck.  This is the ONLY
        per-write bookkeeping the coalescing pipeline does: the repair
        itself is deferred until a read needs the warm state, so a write
        burst costs one batched patch dispatch, not one per write.
        Saturates (value None) past 4x the repair budget so a burst
        can't grow it unbounded — saturation just means 'rebuild, don't
        walk journals'.  Skipped entirely while nothing is warm
        (pure-ingest workloads pay zero here) and when repair is
        disabled (the ledger's only consumer, _serve_state_repair, can
        never use it with a zero budget)."""
        if self.prearmer is not None:
            # Queue a background re-arm for this shape (cheap no-op when
            # the shape was never registered) BEFORE the repair gates:
            # pre-arming covers exactly the writes repair can't absorb.
            self.prearmer.note_invalidate(index, fname)
        if self._repair_rows_max <= 0:
            return
        if not self._serve_states and not self._matrix_cache:
            return
        key = (index, fname)
        cap = 4 * self._repair_rows_max + 16
        with self._dirty_mu:
            cur = self._dirty_rows.get(key, ())
            if cur is None:
                return  # already saturated
            if cur == ():
                cur = self._dirty_rows[key] = set()
            cur.update(int(r) for r in rows)
            if len(cur) > cap:
                self._dirty_rows[key] = None

    def note_external_write(self, index: str, fname: str, rows) -> None:
        """Public hook for non-executor write paths (the streaming
        ingest door and the device bulk-build door) to feed the
        dirty-row ledger, so warm serve state patches instead of
        rebuilding after an ingest burst.  Bulk overlay commits also
        journal their rows inside the fragment (``_log_dirty``), so the
        patch lane can rank-k-update exactly the planes a bulk batch
        touched even though the write bypassed the executor."""
        self._note_dirty_rows(index, fname, rows)

    def _journal_dirty_rows(self, frags, old_gens, new_gens) -> Optional[dict]:
        """The EXACT per-(row, slice) delta written between two generation
        vectors, from the fragment dirty-row journals, as a
        ``{slice_position: rows}`` mapping (positions index the ``frags``
        order, which is the pool's slice order) — or None when the delta
        is unenumerable (bulk import/restore, journal evicted, fragment
        deleted/recreated) or its row UNION is over the repair budget;
        callers then take the full rebuild path.  Keeping each
        fragment's rows separate (instead of the old flat union) is what
        lets the patch lane re-fetch and rank-k-update only the planes
        actually written.  Journals are maintained inside the fragment's
        own locked mutation methods, so this covers every writer — not
        just this executor's write paths."""
        budget = self._repair_rows_max
        if budget <= 0:
            return None
        dirty: dict[int, set] = {}
        union: set = set()
        for si, (f, g0, g1) in enumerate(zip(frags, old_gens, new_gens)):
            if g0 == g1:
                continue
            if f is None:
                return None  # fragment deleted since the state was recorded
            rows = f.rows_dirty_since(g0)
            if rows is None:
                return None
            if rows:
                dirty[si] = rows
                union |= rows
                if len(union) > budget:
                    return None
        return dirty if dirty else None

    def _serve_state_repair(self, key: tuple, st: dict) -> Optional[dict]:
        """The serve-state PATCH lane (the Roaring repair principle one
        level up): a state invalidated by a small write is repaired —
        the pool matrix's dirty rows rewritten in place, the Gram
        rank-k-updated, the glut re-derived — and re-captured with fresh
        validity tokens, instead of being popped and rebuilt from
        scratch.  Returns the re-captured state (read-your-writes: it
        serves post-write counts), or None when the delta is over the
        repair budget, unenumerable, or structural (index/frame/slice
        growth) — the caller pops and the general lane re-arms.
        """
        index, fname = key
        idx_obj = st["idx_obj"]
        if self.holder.index(index) is not idx_obj:
            return None
        if idx_obj.max_slice() != st["max_slice"]:
            return None  # slice/row-count growth: the state's span is wrong
        with self._dirty_mu:
            noted = self._dirty_rows.get(key, ())
        if noted is None or (noted and len(noted) > self._repair_rows_max):
            return None  # ledger precheck: saturated or clearly over budget
        slices: list[int] = []
        frags: list = []
        old_gens: list[int] = []
        new_gens: list[int] = []
        for s, frag, gen in st["slots"]:
            f = self.holder.fragment(index, fname, VIEW_STANDARD, s)
            if f is not frag:
                return None  # fragment created/replaced since capture
            slices.append(s)
            frags.append(f)
            old_gens.append(gen)
            new_gens.append(-1 if f is None else f.generation)
        dirty = self._journal_dirty_rows(frags, old_gens, new_gens)
        if dirty is None:
            return None
        # Drive the pool's patch lane: the per-(row, slice) delta is
        # complete for the (old -> new) span — the whole write burst
        # since capture coalesces into THIS one acquire (one pool
        # rewrite + one rank-k Gram dispatch), and only the planes
        # actually written are re-gathered.  The box (with its glut)
        # survives.
        pool = self._pool_for(index, fname, VIEW_STANDARD, slices)
        _, _, box = pool.acquire([], tuple(new_gens), dirty_rows=dirty)
        glut = box.get("gram_lut")
        if glut is None:
            return None  # box didn't survive (evicted/reset elsewhere)
        self._capture_serve_state(index, fname, slices, glut, box)
        return self._serve_states.get(key)

    def drop_frame_state(self, index: str, frame: str) -> None:
        """Drop every cached serving artifact for one (index, frame):
        serve states, device row pools (and their Grams), multi-view
        Range matrices, the fast-write pin, and the dirty ledger.  Called
        on frame deletion so a recreated namesake can never be served
        from (or pin the memory of) the old frame's device state; the
        generation/identity validity checks already guarantee
        correctness — this hook reclaims the memory eagerly."""
        with self._matrix_mu:
            for k in [k for k in self._matrix_cache if k[0] == index and k[1] == frame]:
                del self._matrix_cache[k]
            for k in [
                k for k in self._multi_matrix_cache if k[0] == index and k[1] == frame
            ]:
                del self._multi_matrix_cache[k]
            self._serve_states.pop((index, frame), None)
            # Per-thread armed lane tables can't be reached from here;
            # the epoch bump makes every thread clear its own at next
            # access (identity revalidation keeps the interim safe).
            self._lane_epoch += 1
        with self._dirty_mu:
            self._dirty_rows.pop((index, frame), None)
        if self.prearmer is not None:
            self.prearmer.forget(index, frame)
        if self.qcache is not None:
            # A recreated namesake frame gets fresh generations (the
            # counter never repeats), so validity already prevents stale
            # serving — the purge reclaims the bytes eagerly.
            self.qcache.purge_frame(index, frame)

    def drop_index_state(self, index: str) -> None:
        """Index-deletion analog of drop_frame_state (every frame)."""
        with self._matrix_mu:
            for k in [k for k in self._matrix_cache if k[0] == index]:
                del self._matrix_cache[k]
            for k in [k for k in self._multi_matrix_cache if k[0] == index]:
                del self._multi_matrix_cache[k]
            for k in [k for k in list(self._serve_states) if k[0] == index]:
                self._serve_states.pop(k, None)
            self._lane_epoch += 1  # see drop_frame_state
        with self._dirty_mu:
            for k in [k for k in self._dirty_rows if k[0] == index]:
                del self._dirty_rows[k]
        if self.prearmer is not None:
            self.prearmer.forget_index(index)
        if self.qcache is not None:
            self.qcache.purge_index(index)

    def _capture_serve_state(self, index: str, fname: str, slices, glut, box) -> None:
        """Snapshot the single-call serve lane's state after a warm-Gram
        single-frame batch: the glut arrays (sorted row ids, positions,
        Gram — immutable snapshots; writes build NEW boxes) plus the
        validity tokens.  Only a FULL contiguous slice range qualifies
        (partial slice sets come from remote/fan-out execution).

        Validity tokens come from ``box["gens"]`` — the generations the
        box's matrix content was validated against at ACQUIRE time — not
        from a fresh read: a write landing between the Gram serve and
        this capture would otherwise stamp post-write generations onto
        pre-write data and every later validity check would pass against
        stale counts.  A fragment replaced/created since acquire makes
        its stored token mismatch (the generation counter is global and
        never repeats), so the state conservatively invalidates.
        """
        idx_obj = self.holder.index(index)
        fr = self.holder.frame(index, fname)
        if idx_obj is None or fr is None:
            return
        gens = box.get("gens")
        if gens is None or len(gens) != len(slices):
            return
        if list(slices) != list(range(len(slices))) or (
            idx_obj.max_slice() != len(slices) - 1
        ):
            return
        try:
            frame_b = fname.encode("ascii")
            rowkey_b = fr.row_label.encode("ascii")
        except UnicodeEncodeError:
            return
        slots = []
        for s, g in zip(slices, gens):
            f = self.holder.fragment(index, fname, VIEW_STANDARD, s)
            slots.append((s, f, g))
        st = {
            "index": index,
            "fname": fname,
            "idx_obj": idx_obj,
            "frame_b": frame_b,
            "rowkey_b": rowkey_b,
            "allow_default": fname == DEFAULT_FRAME,
            "max_slice": len(slices) - 1,
            "slots": slots,
            "glut_id": glut,
            "rs": glut[0],
            "gram": glut[1],
            "ps": glut[2],
        }
        with self._matrix_mu:
            self._serve_states[(index, fname)] = st
            self._serve_states.move_to_end((index, fname))
            while len(self._serve_states) > self._serve_states_max:
                self._serve_states.popitem(last=False)
        # The fresh tokens make older ledger entries moot for THIS frame's
        # precheck; the journals stay authoritative for any other state.
        with self._dirty_mu:
            self._dirty_rows.pop((index, fname), None)

    def _apply_queued_reads(self, items) -> list:
        """Evaluate one drained serve-queue batch of flat-lane requests.

        Requests sharing (index, name tables, slices) concatenate their
        op/frame/row arrays and run through ONE
        ``_fused_local_counts_arrays`` pass — with a warm Gram that is a
        single native call answering every queued request — then split
        back per request.
        """
        results: list = [None] * len(items)
        groups: dict[tuple, list[int]] = {}
        for i, (index, _arrays, tables, slices) in enumerate(items):
            groups.setdefault((index, tables, slices), []).append(i)
        for (index, tables, slices), idxs in groups.items():
            frame_names = [b.decode("utf-8") for b in tables[0]]
            if len(idxs) == 1:
                arrs = items[idxs[0]][1]
                ops, fids, rr1, rr2 = arrs
            else:
                ops = np.concatenate([items[i][1][0] for i in idxs])
                fids = np.concatenate([items[i][1][1] for i in idxs])
                rr1 = np.concatenate([items[i][1][2] for i in idxs])
                rr2 = np.concatenate([items[i][1][3] for i in idxs])
            counts = self._fused_local_counts_arrays(
                index, frame_names, ops, fids, rr1, rr2, list(slices)
            )
            off = 0
            for i in idxs:
                n = len(items[i][1][0])
                results[i] = counts[off : off + n]
                off += n
        return results

    def _fused_local_counts_arrays(
        self, index: str, frame_names, op_ids, frame_ids, r1, r2, slices,
        plan=None, _prearm=False,
    ) -> list[int]:
        """Vectorized local evaluator for the compiled-query lane: group by
        (frame, op) with numpy masks, map row ids to matrix positions via
        searchsorted, and answer each group with one Gram lookup batch or
        kernel dispatch — no per-call Python loop.  With a warm Gram the
        whole batch collapses further into ONE native call
        (pn_gram_counts: binary-search position mapping + count
        identities in C++), the steady-state serving loop.

        ``plan`` is the front door's planner decision (ExecOptions.plan):
        a forced lane overrides the static rm_pool ladder below (the
        eligibility gates still apply), lane None changes nothing, and
        either way each chunk's observed cost folds back through
        Planner.record under the lane that actually ran.  ``_prearm``
        marks the PreArmer's background replay so it doesn't re-register
        itself as a hot shape.
        """
        from pilosa_tpu import native
        from pilosa_tpu.native import PQL_PAIR_OPS

        forced = plan.get("lane") if plan is not None else None
        rec = self.planner is not None and plan is not None and not _prearm
        out = np.zeros(len(op_ids), dtype=np.int64)
        for f_id in np.unique(frame_ids):
            fmask0 = frame_ids == f_id
            fname = frame_names[f_id] if f_id >= 0 else DEFAULT_FRAME
            pool = self._pool_for(index, fname, VIEW_STANDARD, slices)
            rows_all = np.unique(np.concatenate([r1[fmask0], r2[fmask0]]))
            if len(rows_all) <= pool.cap_max:
                qparts = [np.nonzero(fmask0)[0]]
            else:
                # Paging regime: partition the frame's queries so each
                # chunk's unique rows fit the pool; rows stream through
                # HBM chunk by chunk instead of falling back to host.
                qparts = [
                    np.asarray(p)
                    for p in chunk_queries(
                        np.nonzero(fmask0)[0].tolist(),
                        lambda qi: (int(r1[qi]), int(r2[qi])),
                        pool.cap_max,
                    )
                ]
            for qpart in qparts:
                t0 = time.perf_counter() if rec else 0.0
                fmask = np.zeros(len(op_ids), dtype=bool)
                fmask[qpart] = True
                fr1, fr2 = r1[fmask], r2[fmask]
                rows = np.unique(np.concatenate([fr1, fr2]))
                # Tall working sets relative to this chunk's batch hit the
                # gather kernels — page them through the ROW-MAJOR pool
                # lane (one contiguous DMA descriptor per operand row;
                # same choice as the AST fused path), UNLESS the Gram
                # could serve this working set (warm Gram lookups beat
                # any kernel; _gram_could_serve mirrors its gates).  In
                # the paging regime (multiple qparts) the Gram can never
                # WARM — each part switch remaps pool slots and kills the
                # cache box — so only a single-part working set may veto
                # the row-major lane.  Effective rows mirror the
                # slice-major pool's cap (dispatch sees the full matrix).
                # A planner-forced lane replaces this ladder (pin/ledger
                # decisions); the eligibility gates below still apply.
                if forced == "gram":
                    rm_pool = False  # slice-major family: always feasible
                elif forced == "rmgather":
                    rm_pool = getattr(
                        self.engine, "supports_row_major_gather", False
                    )
                else:
                    rm_pool = (
                        getattr(self.engine, "supports_row_major_gather", False)
                        and (
                            len(qparts) > 1
                            or not self._gram_could_serve(len(rows), len(slices))
                        )
                        and self.engine.prefer_rowmajor(
                            max(len(rows), pool.cap), len(slices), _WORDS,
                            int(fmask.sum()), 2,
                        )
                    )
                if rm_pool and len(rows) > self._peek_pool_cap(
                    index, fname, VIEW_STANDARD, slices, lane="rmgather"
                ):
                    rm_pool = False  # diverged lane caps: stay chunkable
                id_pos, matrix, box = self._frame_matrix(
                    index, fname, slices, set(rows.tolist()),
                    lane="rmgather" if rm_pool else "",
                )
                gram = None if rm_pool else self._frame_gram(matrix, box)
                if gram is not None:  # implies a live box (_frame_gram contract)
                    # Native lane: the gram_lut (sorted id table + positions)
                    # lives and dies with the cache box, like the Gram itself.
                    glut = box.get("gram_lut")
                    if glut is None:
                        rs = np.array(sorted(id_pos), dtype=np.int64)
                        ps = np.fromiter(
                            (id_pos[int(v)] for v in rs), dtype=np.int32, count=len(rs)
                        )
                        glut = box["gram_lut"] = (rs, np.ascontiguousarray(gram), ps)
                    # Mask indexing yields fresh C-contiguous arrays, so the
                    # raw pointers hand off to C directly.
                    counts = native.gram_counts(
                        op_ids[fmask], fr1, fr2, glut[0], glut[2], glut[1]
                    )
                    if counts is not None:
                        out[fmask] = counts
                        # Arm the single-call serve lane: this exact
                        # state (frame + glut) just served natively, so
                        # subsequent requests can skip straight to
                        # pn_serve_pairs — or, when a batch spans several
                        # frames, to pn_serve_multi (each frame group
                        # arms its own state here).  Unpaged working sets
                        # only; re-capture only when the glut changed.
                        st = self._serve_states.get((index, fname))
                        if (
                            len(qparts) == 1
                            and (st is None or st["glut_id"] is not glut)
                        ):
                            self._capture_serve_state(index, fname, slices, glut, box)
                        if rec:
                            self.planner.record(
                                index=index, fp=plan["fp"], lane="gram",
                                ms=(time.perf_counter() - t0) * 1e3, plan=plan,
                            )
                        continue
                lut = np.fromiter(
                    (id_pos[int(rv)] for rv in rows), dtype=np.int32, count=len(rows)
                )
                p1 = lut[np.searchsorted(rows, fr1)]
                p2 = lut[np.searchsorted(rows, fr2)]
                fops = op_ids[fmask]
                fout = np.zeros(len(fr1), dtype=np.int64)
                for op_id in np.unique(fops):
                    om = fops == op_id
                    pairs = np.stack([p1[om], p2[om]], axis=1).astype(np.int32)
                    op = PQL_PAIR_OPS[int(op_id)]
                    if gram is not None:
                        from pilosa_tpu.ops.bitwise import gram_pair_counts

                        counts = gram_pair_counts(op, gram, pairs)
                    elif rm_pool:
                        counts = self.engine.to_numpy(
                            self.engine.gather_count_rowmajor_dev(op, matrix, pairs)
                        ).astype(np.int64)
                    else:
                        counts = self.engine.gather_count(op, matrix, pairs)
                    fout[om] = counts
                out[fmask] = fout
                if rec:
                    # Fold the chunk's cost back under the lane that
                    # ACTUALLY ran (an eligibility veto self-corrects).
                    self.planner.record(
                        index=index, fp=plan["fp"],
                        lane="rmgather" if rm_pool else "gram",
                        ms=(time.perf_counter() - t0) * 1e3, plan=plan,
                    )
        if self.prearmer is not None and not _prearm:
            # Register/refresh this batch as the (index, frame) replay
            # thunk: re-running it through the ordinary path re-arms
            # matrix, Gram, and serve state after an invalidating write.
            thunk = partial(
                self._fused_local_counts_arrays,
                index, frame_names, np.array(op_ids), np.array(frame_ids),
                np.array(r1), np.array(r2), list(slices), _prearm=True,
            )
            for f_id in np.unique(frame_ids):
                fname = frame_names[f_id] if f_id >= 0 else DEFAULT_FRAME
                self.prearmer.note_shape(index, str(fname), thunk)
        return out.tolist()

    def _tree_build(self, index: str, c: pql.Call, fv_box: dict):
        """Recursively compile a bitmap call tree to leaf/op-node form.

        Returns int (a Bitmap leaf's row id) or (op_id, left, right).
        Raises _TreeUnfusable for shapes outside the lane (Range leaves,
        <2-child nodes, mixed frame/view) and PilosaError for invalid
        leaves (callers abort the whole fuse so the sequential path
        surfaces the identical error)."""
        if c.name == "Bitmap":
            frame, view, row = self._resolve_bitmap_leaf(index, c)
            if fv_box["fv"] is None:
                fv_box["fv"] = (frame, view)
            elif fv_box["fv"] != (frame, view):
                raise _TreeUnfusable()
            return int(row)
        op = self._FUSABLE_OPS.get(c.name)
        if op is None or len(c.children) < 2:
            raise _TreeUnfusable()
        subs = [self._tree_build(index, ch, fv_box) for ch in c.children]
        if op == "andnot":
            # a &~ b &~ c ... == a & ~(b | c | ...) — the rest joins
            # under a balanced OR so Difference nests in log depth too.
            rest = (
                subs[1]
                if len(subs) == 2
                else _tree_balanced(_TREE_OP_IDS["or"], subs[1:])
            )
            return (_TREE_OP_IDS["andnot"], subs[0], rest)
        return _tree_balanced(_TREE_OP_IDS[op], subs)

    def _compile_count_tree(self, index: str, ch: pql.Call):
        """Compile one Count child tree for the fused tree lane.

        Returns (frame, view, ("tree", 2^D), leaves, opc) or None when the
        shape stays sequential; propagates PilosaError for invalid leaves.
        """
        box = {"fv": None}
        try:
            node = self._tree_build(index, ch, box)
        except _TreeUnfusable:
            return None
        if isinstance(node, int):
            return None
        d = _tree_depth(node)
        if d > _TREE_DEPTH_MAX:
            return None
        # Pad slots gather the leftmost REAL leaf so the unique-row
        # working set (pool capacity, Gram eligibility) never grows.
        fill = node
        while not isinstance(fill, int):
            fill = fill[1]
        leaves, opc = _tree_flatten(_tree_pad(node, d, fill), d)
        frame, view = box["fv"]
        return frame, view, ("tree", 1 << d), tuple(leaves), tuple(opc)

    def _fuse_count_pair_batch(
        self, index: str, calls, slices, inv_slices, opt: ExecOptions
    ) -> Optional[dict[int, int]]:
        """Run all Count(<op>(Bitmap, Bitmap, ...)) calls in a request as
        fused device dispatches (one per distinct op/arity group).

        The TPU-native replacement for issuing the hot query shapes
        (executor.go:576-605) one call at a time: row ids are gathered by
        the kernel straight from a device-resident row matrix
        (ops.dispatch.gather_count / gather_count_multi), so a request
        carrying a batch of count queries costs one kernel launch per
        op/arity group instead of per-call row uploads + reductions.
        Covers Intersect, Union, and Difference over 2+ Bitmap children
        (2-operand calls keep the Gram-eligible pair lane), Xor over
        exactly two — and, via the TREE lane, ARBITRARY nestings of the
        four ops (mixed Intersect(Union(...), ...) trees, multi-operand
        Xor) up to depth 4, compiled to per-query perfect-tree opcode
        programs and dispatched once per depth bucket
        (executor.go:261-276's uniform any-depth evaluation, fused).
        Distributed requests forward ONE batch per remote node and fuse
        locally per node.
        """
        if not slices:
            return None

        # call idx -> (frame, view, kernel_op, row-id tuple) for flat
        # calls, or (frame, view, ("tree", 2^D), leaves, opc) for nested
        # trees / multi-operand Xor (the fused tree lane).
        matched: dict[int, tuple] = {}
        batch_view: Optional[str] = None
        for i, c in enumerate(calls):
            if c.name != "Count" or len(c.children) != 1:
                continue
            ch = c.children[0]
            if ch.name == "Bitmap":
                # Plain row count: |r| == |r & r| — rides the pair lane
                # (Gram diagonal) so a dashboard mixing row counts with
                # pair counts keeps the whole batch fused.
                try:
                    frame, view, row_id = self._resolve_bitmap_leaf(index, ch)
                except PilosaError:
                    return None  # surface the error through the normal path
                if batch_view is None:
                    batch_view = view
                elif view != batch_view:
                    return None
                matched[i] = (frame, view, "and", (row_id, row_id))
                continue
            op = self._FUSABLE_OPS.get(ch.name)
            if op is None or len(ch.children) < 2:
                continue
            entry = None
            if op != "xor" or len(ch.children) == 2:
                # Flat attempt first: the pair lane is Gram-eligible and
                # the multi-fold lane gathers K rows vs the tree lane's
                # 2^ceil(log2 K).
                leaves = []
                for leaf in ch.children:
                    if leaf.name != "Bitmap":
                        break
                    try:
                        frame, view, row_id = self._resolve_bitmap_leaf(index, leaf)
                    except PilosaError:
                        return None  # surface the error through the normal path
                    leaves.append((frame, view, row_id))
                if len(leaves) == len(ch.children) and all(
                    l[:2] == leaves[0][:2] for l in leaves[1:]
                ):
                    entry = (
                        leaves[0][0],
                        leaves[0][1],
                        op,
                        tuple(l[2] for l in leaves),
                    )
            if entry is None:
                # Nested / multi-Xor shapes: the tree lane (one dispatch
                # per depth bucket — executor.go:261-276's any-depth
                # uniformity, fused).
                try:
                    entry = self._compile_count_tree(index, ch)
                except PilosaError:
                    return None  # surface the error through the normal path
                if entry is None:
                    continue
            # Uniform view across the batch: the slice domain (standard vs
            # inverse axis) is per-mapReduce, so mixed-view requests take
            # the sequential path.
            if batch_view is None:
                batch_view = entry[1]
            elif entry[1] != batch_view:
                return None
            matched[i] = entry
        # Fuse only when the WHOLE request is fusable reads: a write call
        # anywhere in the request must be observed by later Counts
        # (per-call ordering semantics), so mixed requests take the
        # sequential path.
        if len(matched) < 2 or len(matched) != len(calls):
            return None

        if batch_view != VIEW_STANDARD and inv_slices is not None:
            slices = inv_slices  # inverse axis has its own max slice
        if not slices:
            return None

        idxs = sorted(matched)
        totals = self._fused_dispatch(
            index, idxs, slices, opt,
            lambda: pql.Query(calls=[calls[i] for i in idxs]),
            lambda node_slices: self._fused_local_counts(
                index, matched, idxs, node_slices, plan=opt.plan
            ),
        )
        return dict(zip(idxs, totals))

    def _fuse_count_range_batch(
        self, index: str, calls, slices, opt: ExecOptions
    ) -> Optional[dict[int, int]]:
        """Run an all-``Count(Range(...))`` request as fused device
        dispatches: the per-call view covers (time.go:95-167) become rows
        of ONE multi-view matrix and every query's union+popcount happens
        in one kernel batch (dispatch.gather_count_or_multi) instead of
        per-call view gathers and OR chains.  Same fusion contract as the
        pair path: only fires when the WHOLE request matches, everything
        else falls back to the sequential path with identical errors.
        """
        if not slices or len(calls) < 2:
            return None
        if len(slices) > _INT32_SAFE_SLICES:
            # One fused dispatch spans every slice; past the int32 count
            # bound the sequential per-call path (host-summed python ints)
            # keeps Range counts exact.
            return None
        matched: dict[int, tuple[str, int, list[str]]] = {}
        for i, c in enumerate(calls):
            if c.name != "Count" or len(c.children) != 1:
                return None
            ch = c.children[0]
            if ch.name != "Range" or ch.children:
                return None
            try:
                frame_name, frame, row_id, start, end = self._parse_range_args(index, ch)
            except PilosaError:
                return None  # surface the error through the normal path
            views = (
                tq.views_by_time_range(VIEW_STANDARD, start, end, frame.time_quantum)
                if frame.time_quantum
                else []
            )
            matched[i] = (frame_name, row_id, views)

        # Working-set guard: fusing pays through the cached multi-view
        # matrix; a request whose distinct (frame, view, row) combos
        # exceed the matrix row budget would rebuild+re-upload a giant
        # matrix every time, so it takes the sequential path instead
        # (per-fragment device row caches amortize there).
        combos = {(f, v, r) for f, r, views in matched.values() for v in views}
        if len(combos) > self._matrix_rows_max:
            return None

        idxs = sorted(matched)
        totals = self._fused_dispatch(
            index, idxs, slices, opt,
            lambda: pql.Query(calls=[calls[i] for i in idxs]),
            lambda node_slices: self._fused_local_range_counts(index, matched, idxs, node_slices),
        )
        return dict(zip(idxs, totals))

    def _fused_local_range_counts(
        self, index: str, matched: dict, idxs: list[int], slices
    ) -> list[int]:
        """Fused Range counts for a slice batch, aligned with idxs.

        Builds one matrix per frame whose rows are the distinct
        (view, row_id) combos referenced by the batch, pads each call's
        cover to the batch max by repeating its first row (OR-idempotent),
        and answers the whole frame group in one engine dispatch."""
        slices = list(slices or [])
        out: dict[int, int] = {}
        if not slices:
            return [0] * len(idxs)
        by_frame: dict[str, list[int]] = {}
        for i in idxs:
            by_frame.setdefault(matched[i][0], []).append(i)
        for frame_name, f_idxs in by_frame.items():
            live = [i for i in f_idxs if matched[i][2]]
            for i in f_idxs:
                if not matched[i][2]:
                    out[i] = 0  # no quantum / empty cover (zeros segment)
            if not live:
                continue
            combos = sorted(
                {(v, matched[i][1]) for i in live for v in matched[i][2]}
            )
            id_pos, matrix, memo = self._multi_view_matrix(index, frame_name, slices, combos)
            # Count memo: the memo dict lives and dies with the cache entry
            # (fresh on any write), so repeated ranges — the dashboard
            # steady state — are answered host-side with zero device work,
            # the Range analog of the Gram lane's count lookups.
            misses = []
            for i in live:
                _, row_id, views = matched[i]
                c = memo.get((row_id, tuple(views)))
                if c is None:
                    misses.append(i)
                else:
                    out[i] = c
            if misses:
                # On jitted engines, CANONICAL kernel shapes: the batch dim
                # is chunked to a fixed 128 (padded by repeating the first
                # miss's cover — extra counts computed and discarded) and
                # the cover width padded to one of {4, 16, 64}
                # (repeat-first-id padding is OR-idempotent).  Ragged
                # shapes would trigger a jit recompile per distinct
                # (miss count, max cover) pair — seconds each.  Engines
                # without jit (numpy) use exact shapes: padding there is
                # pure wasted gather/OR work.
                vmax = max(len(matched[i][2]) for i in misses)
                static = getattr(self.engine, "wants_static_shapes", False)
                if static:
                    vb = 4 if vmax <= 4 else 16 if vmax <= 16 else 64 if vmax <= 64 else vmax
                    BB = 128
                else:
                    vb, BB = vmax, len(misses)
                for c0 in range(0, len(misses), BB):
                    part = misses[c0 : c0 + BB]
                    idx_arr = np.zeros((BB, vb), dtype=np.int32)
                    for k, i in enumerate(part):
                        _, row_id, views = matched[i]
                        cover = [id_pos[(v, row_id)] for v in views]
                        idx_arr[k, : len(cover)] = cover
                        idx_arr[k, len(cover):] = cover[0]
                    idx_arr[len(part):] = idx_arr[0]
                    counts = self.engine.gather_count_or_multi(matrix, idx_arr)
                    for k, i in enumerate(part):
                        c = int(counts[k])
                        out[i] = c
                        if len(memo) < 65536:  # bound host memory vs adversarial
                            memo[(matched[i][1], tuple(matched[i][2]))] = c
        return [out[i] for i in idxs]

    def _multi_view_matrix(
        self, index: str, frame: str, slices, combos: list[tuple[str, int]]
    ) -> tuple[dict[tuple[str, int], int], object, dict]:
        """Engine matrix [n_slices, len(combos), W] whose row planes are
        (view, row_id) combos — the fused Range path's working set — plus
        a per-entry count memo for repeated covers.

        Cached like the single-view matrix (LRU, validated by the write
        generations of every (view, slice) fragment involved); rebuilt
        whole on any change (Range covers touch many small time views, so
        per-plane patching buys little).  The memo dict is shared across
        threads without a lock: entries are deterministic pure counts, so
        a racing double-compute stores the same value.
        """
        # Keyed by (index, frame, slices) — NOT the view set: a batch whose
        # union of Range covers introduces a new view must take the append
        # path below, not miss the whole entry (heterogeneous dashboard
        # batches cycle distinct view sets; per-view-set keys would thrash
        # the small LRU with rebuild+re-upload).  Views live inside the
        # (view, row) combo space; generations are tracked per (view,
        # slice) for every view resident in the matrix.
        key = (index, frame, tuple(slices))
        with self._matrix_mu:
            hit = self._multi_matrix_cache.get(key)
        old_id_pos = old_matrix = old_memo = None
        old_views: list[str] = []
        if hit is not None:
            old_gens, old_id_pos, old_matrix, old_memo = hit
            old_views = sorted(old_gens)
        views = sorted({v for v, _ in combos} | set(old_views))
        frags = {
            v: [self.holder.fragment(index, frame, v, s) for s in slices]
            for v in views
        }
        gens = {
            v: tuple(-1 if f is None else f.generation for f in frags[v])
            for v in views
        }
        missing: list[tuple[str, int]] = []
        if old_id_pos is not None:
            if all(gens[v] == old_gens[v] for v in old_views):
                missing = sorted(set(combos) - old_id_pos.keys())
                if not missing:
                    with self._matrix_mu:
                        if key in self._multi_matrix_cache:
                            self._multi_matrix_cache.move_to_end(key)
                    return old_id_pos, old_matrix, old_memo
            else:
                old_id_pos = None  # writes: rebuild, fresh memo

        def densify(combo_list, cap):
            """[n_slices, cap, W] host block; rows beyond the combo list
            stay zero (capacity padding — gathers never index them)."""
            planes = []
            for si in range(len(slices)):
                block = np.zeros((cap, _WORDS), dtype=np.uint32)
                for k, (v, r) in enumerate(combo_list):
                    f = frags[v][si]
                    if f is not None:
                        block[k] = f.row_dense(r)
                planes.append(block)
            return np.stack(planes)

        def pow2(n: int) -> int:
            return 1 << (n - 1).bit_length() if n > 1 else 1

        if old_id_pos is not None and len(old_id_pos) + len(missing) <= self._matrix_rows_max:
            # Generations unchanged, new combos only: write them into the
            # cached matrix's spare capacity, then append any overflow as a
            # new power-of-two capacity block — and KEEP the memo (its
            # counts are still valid).  Physical positions are assigned
            # where the rows actually land (spare rows first, then the
            # appended block), so id_pos always matches the matrix.
            # Power-of-two capacity keeps the matrix SHAPE stable across
            # most appends, so downstream jitted kernels rarely recompile.
            n_old = 1 + max(old_id_pos.values()) if old_id_pos else 0
            cap = old_matrix.shape[1]
            spare = missing[: cap - n_old]
            overflow = missing[len(spare):]
            matrix = old_matrix
            if spare:
                matrix = self.engine.set_rows(matrix, n_old, densify(spare, len(spare)))
            if overflow:
                new_cap = pow2(cap + len(overflow))
                matrix = self.engine.append_rows(
                    matrix, densify(overflow, new_cap - cap)
                )
            id_pos = dict(old_id_pos)
            for k, c in enumerate(spare):
                id_pos[c] = n_old + k
            for k, c in enumerate(overflow):
                id_pos[c] = cap + k
            memo = old_memo
            with self._matrix_mu:
                self._multi_matrix_cache[key] = (gens, id_pos, matrix, memo)
                self._multi_matrix_cache.move_to_end(key)
                while len(self._multi_matrix_cache) > self._matrix_cache_entries:
                    self._multi_matrix_cache.popitem(last=False)
            return id_pos, matrix, memo

        id_pos = {c: k for k, c in enumerate(combos)}
        matrix = self.engine.matrix(densify(combos, pow2(len(combos))))
        memo = {}
        # Store generations only for views actually resident in the matrix:
        # a rebuild drops old views whose combos this batch no longer
        # references, and tracking their gens would invalidate the entry on
        # writes to rows it doesn't even hold.
        store_gens = {v: gens[v] for v in sorted({vv for vv, _ in combos})}
        if len(combos) <= self._matrix_rows_max:
            with self._matrix_mu:
                self._multi_matrix_cache[key] = (store_gens, id_pos, matrix, memo)
                self._multi_matrix_cache.move_to_end(key)
                while len(self._multi_matrix_cache) > self._matrix_cache_entries:
                    self._multi_matrix_cache.popitem(last=False)
        return id_pos, matrix, memo

    def _is_distributed(self, opt: ExecOptions) -> bool:
        """Whether this executor coordinates a multi-node fan-out (shared
        by the AST fused path and the compiled-query lane)."""
        return (
            not opt.remote
            and self.cluster is not None
            and self.client_factory is not None
            and len(self.cluster.nodes) > 1
        )

    def _fused_dispatch(
        self, index: str, idxs: list[int], slices, opt: ExecOptions,
        batch_query_fn, local_fn,
    ) -> list[int]:
        """Run a matched fused count batch locally or cluster-wide.

        Distributed fusion: ONE forwarded batch request per remote node
        (N fused calls x M nodes = M requests, not N*M per-call forwards),
        local slices through the fused kernels via ``local_fn(slices)``
        (pair counts or Range covers), and the same mid-query replica
        failover as per-call mapReduce.  ``batch_query_fn`` builds the
        Query to forward — called only when a remote hop exists, so
        AST-free callers (the flat fast lane) stay AST-free single-node.
        The remote peer re-enters the fused path with opt.remote=True and
        fuses its own slice batch.
        """
        if not self._is_distributed(opt):
            return local_fn(slices)

        batch_query = batch_query_fn()

        def local_map(node_slices):
            return local_fn(node_slices)

        def remote_map(client, node_slices, trace_span=None):
            # Conditional kwargs: custom client factories (tests,
            # embedders) need not know the QoS/qcache kwargs.
            kw = {}
            if opt.deadline is not None:
                kw["deadline"] = opt.deadline
            if opt.no_cache:
                kw["no_cache"] = True  # a bypass bypasses peer caches too
            if trace_span is not None:
                kw["trace_span"] = trace_span
            res = client.execute_remote(index, batch_query, node_slices, **kw)
            if len(res) != len(idxs):
                raise PilosaError(
                    f"fused batch: peer returned {len(res)} results for {len(idxs)} calls"
                )
            return [int(r) for r in res]

        return self._map_reduce(
            index,
            None,
            slices,
            opt,
            local_map,
            lambda a, b: [x + y for x, y in zip(a, b)],
            [0] * len(idxs),
            remote_map=remote_map,
        )

    def _fused_local_counts(
        self, index: str, matched: dict, idxs: list[int], slices, plan=None
    ) -> list[int]:
        """Fused counts for the given slice batch, aligned with idxs.

        2-operand groups keep the pair lane (Gram-eligible); 3+-operand
        groups run the multi-fold kernel with the operand axis padded to
        a power-of-two bucket (fold-idempotent pad: the first operand for
        and/or, the second for andnot) so jitted shapes stay stable.
        Batches whose unique row set exceeds the pool capacity are chunked
        (rows page through HBM per chunk) instead of falling back to host.

        ``plan`` (ExecOptions.plan, see _fused_local_counts_arrays): a
        forced lane overrides the resident-regime rm_pool ladder, and
        each resident part's cost folds back through Planner.record.
        The streaming regime has no lane choice to plan, so it neither
        applies nor records plans.
        """
        forced = plan.get("lane") if plan is not None else None
        rec = self.planner is not None and plan is not None
        slices = list(slices or [])
        out: dict[int, int] = {}
        if not slices:
            return [0] * len(idxs)
        static = getattr(self.engine, "wants_static_shapes", False)
        # One row pool per (frame, view): unique row ids -> device slots.
        by_fv: dict[tuple[str, str], list[int]] = {}
        for i in idxs:
            by_fv.setdefault(tuple(matched[i][:2]), []).append(i)
        for (frame, view), f_idxs in by_fv.items():
            pool = self._pool_for(index, frame, view, slices)
            # Row-chunk bound: the pool's budgeted capacity, but never so
            # small that chunking degenerates (at huge slice counts the
            # budget shrinks cap below usefulness — those shapes stream
            # the SLICE axis below instead of pooling).
            row_cap = max(64, pool.cap_max)
            # oversize_ok: one Count over more operands than row_cap has no
            # valid row-chunking — it becomes its own part and the
            # streaming branch below (which handles any row count) runs it.
            parts = list(chunk_queries(
                f_idxs, lambda i: matched[i][3], row_cap, oversize_ok=True
            ))
            for part in parts:
                want = sorted({x for i in part for x in matched[i][3]})
                # Group calls by (op, operand-count bucket): one dispatch
                # each.  Jitted engines bucket the operand axis to powers
                # of two (stable shapes); the numpy engine uses exact
                # arities — padding there is pure wasted gather/fold work
                # (same policy as the fused Range lane).
                groups: dict[tuple, list[int]] = {}
                for i in part:
                    k = len(matched[i][3])
                    kb = 2 if k == 2 else (1 << (k - 1).bit_length()) if static else k
                    groups.setdefault((matched[i][2], kb), []).append(i)
                # Tree groups have no row-major kernel (their matrices
                # stay slice-major); a part carrying one keeps every
                # group on the slice-major lanes.
                has_tree = any(isinstance(g[0], tuple) for g in groups)

                if len(want) <= pool.cap_max and len(slices) <= _INT32_SAFE_SLICES:
                    # Resident regime: rows live (or page) in the pool.
                    # (Past _INT32_SAFE_SLICES the single-dispatch count
                    # could overflow the kernels' int32 accumulators at
                    # full density — those shapes stream the slice axis
                    # below, which chunks to the safe bound and sums in
                    # int64 host-side.)
                    # Tall working sets relative to the request batch hit
                    # the GATHER kernels, which on v5e are DMA-descriptor
                    # -bound: those parts page through a ROW-MAJOR pool
                    # lane (one contiguous descriptor per operand row)
                    # instead.  The Gram never engages at these row
                    # counts (its all-pairs work would dwarf the batch).
                    n_pairs = sum(
                        len(v) for (_o, kb), v in groups.items() if kb == 2
                    )
                    # Effective row count mirrors what dispatch will see:
                    # the slice-major pool dispatches over its FULL cap
                    # (not just this part's rows), so a grown pool forces
                    # the gather kernels even for small wants.  Never
                    # displace a Gram-eligible working set — warm Gram
                    # serving (host lookups) beats any per-query kernel —
                    # but only a SINGLE-part working set may veto: in the
                    # paging regime each part switch remaps pool slots
                    # and kills the cache box, so the Gram never warms.
                    # A planner-forced lane replaces this ladder; tree
                    # groups (no row-major kernel) and engine support
                    # still gate it.
                    t0 = time.perf_counter() if rec else 0.0
                    if forced == "gram":
                        rm_pool = False  # slice-major: always feasible
                    elif forced == "rmgather":
                        rm_pool = not has_tree and getattr(
                            self.engine, "supports_row_major_gather", False
                        )
                    else:
                        rm_pool = (
                            not has_tree
                            and getattr(self.engine, "supports_row_major_gather", False)
                            and (
                                len(parts) > 1
                                or not self._gram_could_serve(len(want), len(slices))
                            )
                            and self.engine.prefer_rowmajor(
                                max(len(want), pool.cap), len(slices), _WORDS,
                                n_pairs, max(kb for _, kb in groups),
                            )
                        )
                    if rm_pool and len(want) > self._peek_pool_cap(
                        index, frame, view, slices, lane="rmgather"
                    ):
                        # Lane caps can diverge when one is overridden;
                        # never let the lane switch turn a chunkable part
                        # into an over-capacity error.
                        rm_pool = False
                    id_pos, matrix, box = self._frame_matrix(
                        index, frame, slices, set(want), view,
                        lane="rmgather" if rm_pool else "",
                    )
                    # The Gram only answers 2-operand counts — don't
                    # trigger its (expensive, cached) build for requests
                    # without a pair group.
                    gram = (
                        self._frame_gram(matrix, box)
                        if not rm_pool and any(kb == 2 for _, kb in groups)
                        else None
                    )
                    for gk, op_idxs in sorted(groups.items(), key=_group_sort_key):
                        counts = self.engine.to_numpy(
                            self._group_counts(
                                gk, op_idxs, matched, id_pos, matrix, static,
                                gram, row_major=rm_pool,
                            )
                        )
                        for k2, i in enumerate(op_idxs):
                            out[i] = int(counts[k2])
                    if rec:
                        # Lane that ACTUALLY ran (a veto self-corrects).
                        self.planner.record(
                            index=index, fp=plan["fp"],
                            lane="rmgather" if rm_pool else "gram",
                            ms=(time.perf_counter() - t0) * 1e3, plan=plan,
                        )
                else:
                    # Streaming regime (SURVEY §7 hard part (d) at scale):
                    # the working set exceeds the HBM pool budget, so the
                    # SLICE axis is chunked — each chunk's rows are
                    # densified host-side, moved once, counted, and
                    # discarded; per-query counts accumulate across
                    # chunks.  Device results stay un-fetched inside the
                    # loop (gather_count_dev) so chunk k+1's upload
                    # pipelines behind chunk k's kernel.
                    id_pos = {r: k for k, r in enumerate(want)}
                    s_chunk = self._slice_chunk(len(want))
                    # Tall row sets hit the GATHER kernels, whose v5e
                    # throughput is DMA-descriptor-bound: a row-major
                    # transient gives one contiguous descriptor per
                    # operand (2-4x the slice-major kernel's rate).  The
                    # widest group's operand count must fit the kernels'
                    # VMEM row buffers at this chunk's slice width.
                    row_major = (
                        not has_tree
                        and getattr(self.engine, "supports_row_major_gather", False)
                        and self.engine.rowmajor_ok(
                            min(s_chunk, len(slices)), _WORDS,
                            max(kb for _, kb in groups),
                        )
                    )
                    acc: dict[tuple, list] = {}
                    for c0 in range(0, len(slices), s_chunk):
                        matrix = self._transient_matrix(
                            index, frame, view, slices[c0 : c0 + s_chunk], want,
                            row_major=row_major,
                        )
                        for gk, op_idxs in sorted(groups.items(), key=_group_sort_key):
                            acc.setdefault(gk, []).append(
                                self._group_counts(
                                    gk, op_idxs, matched, id_pos, matrix, static,
                                    None, row_major=row_major,
                                )
                            )
                    for gk, op_idxs in sorted(groups.items(), key=_group_sort_key):
                        total = sum(
                            self.engine.to_numpy(a).astype(np.int64) for a in acc[gk]
                        )
                        for k2, i in enumerate(op_idxs):
                            out[i] = int(total[k2])
        return [out[i] for i in idxs]

    def _group_counts(
        self, gk, op_idxs, matched, id_pos, matrix, static, gram, row_major=False
    ):
        """One fused dispatch for an (op, arity-bucket) call group; returns
        the engine-native count array (fetch deferred to the caller).
        Metered as the "gather" lane (cost attribution): dispatch wall
        time + any host->device operand bytes the engine ledger sees."""
        if self.meter is not None:
            with self.meter.measure("gather"):
                return self._group_counts_inner(
                    gk, op_idxs, matched, id_pos, matrix, static, gram,
                    row_major=row_major,
                )
        return self._group_counts_inner(
            gk, op_idxs, matched, id_pos, matrix, static, gram,
            row_major=row_major,
        )

    def _group_counts_inner(
        self, gk, op_idxs, matched, id_pos, matrix, static, gram, row_major=False
    ):
        op, kb = gk
        if isinstance(op, tuple):  # ("tree", K): nested expression trees
            k = op[1]
            n = len(op_idxs)
            bb = (1 << (n - 1).bit_length()) if (static and n > 1) else n
            leaves = np.zeros((bb, k), dtype=np.int32)
            opc = np.zeros((bb, k - 1), dtype=np.int32)
            for r, i in enumerate(op_idxs):
                leaves[r] = [id_pos[x] for x in matched[i][3]]
                opc[r] = matched[i][4]
            leaves[n:] = leaves[0]  # pad rows repeat the first query
            opc[n:] = opc[0]
            return self.engine.gather_count_tree_dev(matrix, leaves, opc)
        if kb == 2:
            pairs = np.array(
                [
                    [id_pos[matched[i][3][0]], id_pos[matched[i][3][1]]]
                    for i in op_idxs
                ],
                dtype=np.int32,
            )
            if gram is not None:
                # Lazy import is safe here: a non-None Gram implies the
                # jax engine built it, so jax is already loaded.
                from pilosa_tpu.ops.bitwise import gram_pair_counts

                return gram_pair_counts(op, gram, pairs)
            if row_major:
                return self.engine.gather_count_rowmajor_dev(op, matrix, pairs)
            return self.engine.gather_count_dev(op, matrix, pairs)
        # Jitted engines get a padded batch bucket too (pad rows repeat
        # the first call's operands; extra counts discarded) — ragged B
        # recompiles per group size.
        n = len(op_idxs)
        bb = (1 << (n - 1).bit_length()) if (static and n > 1) else n
        idx_arr = np.zeros((bb, kb), dtype=np.int32)
        for r, i in enumerate(op_idxs):
            pos = [id_pos[x] for x in matched[i][3]]
            idx_arr[r, : len(pos)] = pos
            idx_arr[r, len(pos):] = pos[0] if op != "andnot" else pos[1]
        idx_arr[n:] = idx_arr[0]
        if row_major:
            return self.engine.gather_count_multi_rowmajor_dev(op, matrix, idx_arr)
        return self.engine.gather_count_multi_dev(op, matrix, idx_arr)

    def _stream_bytes(self) -> int:
        """Per-chunk byte budget for slice-streaming transient matrices
        (ctor/Config > deprecated env spelling > default)."""
        if self._stream_bytes_cfg > 0:
            return self._stream_bytes_cfg
        # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
        return int(os.environ.get("PILOSA_TPU_STREAM_BYTES", str(1 << 31)))  # analysis-ok: env-knob-outside-config: deprecated spelling for directly-constructed executors

    def _slice_chunk(self, n_rows: int) -> int:
        """Slices per streaming chunk: the byte budget AND the int32
        count bound — a full-density chunk counts up to s_chunk * 2^20
        per query inside the kernels' int32 accumulators, so no chunk may
        span more than _INT32_SAFE_SLICES regardless of budget."""
        return max(
            1,
            min(
                self._stream_bytes() // max(1, n_rows * _WORDS * 4),
                _INT32_SAFE_SLICES,
            ),
        )

    def _densify_block(
        self, index, frame, view, chunk_slices, rows, row_major=False
    ) -> np.ndarray:
        """Host block of dense rows: uint32[len(chunk_slices), len(rows), W]
        (slice-major — pool fetches and transient streaming matrices), or
        [len(rows), len(chunk_slices), W] with ``row_major=True`` (the
        streaming gather lane: each row's slices contiguous for one-descriptor
        DMAs).  Filled directly in target order — no transpose copy."""
        if row_major:
            block = np.zeros((len(rows), len(chunk_slices), _WORDS), dtype=np.uint32)
        else:
            block = np.zeros((len(chunk_slices), len(rows), _WORDS), dtype=np.uint32)
        for bi, s in enumerate(chunk_slices):
            f = self.holder.fragment(index, frame, view, s)
            if f is not None:
                for k, r in enumerate(rows):
                    if row_major:
                        block[k, bi] = f.row_dense(r)
                    else:
                        block[bi, k] = f.row_dense(r)
        return block

    def _transient_matrix(
        self, index, frame, view, chunk_slices, rows_sorted, row_major=False
    ):
        """One slice chunk's transient matrix, built host-side and moved
        in a single transfer; NOT cached — streaming shapes would evict
        every steady-state pool for nothing."""
        block = self._densify_block(
            index, frame, view, chunk_slices, rows_sorted, row_major=row_major
        )
        if self.meter is not None:
            # Streaming lane: the chunk upload is the cost (the chunk's
            # dispatches meter separately as "gather").
            with self.meter.measure("stream"):
                if row_major:
                    return self.engine.matrix_rows(block)
                return self.engine.matrix(block)
        if row_major:
            return self.engine.matrix_rows(block)
        return self.engine.matrix(block)

    def _gram_env(self) -> tuple[bool, int]:
        """(no_gram, rows_max) — read once per Executor: these sit on the
        per-request serving path and os.environ lookups cost ~10 us each
        (same lazy-cache pattern as Fragment._max_opn_scale).  Process-
        lifetime settings; tests that toggle them build fresh Executors."""
        cached = self._gram_env_cache
        if cached is None:
            no_gram = self._no_gram_cfg
            if no_gram is None:
                # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
                no_gram = os.environ.get("PILOSA_TPU_NO_GRAM", "").lower() in (  # analysis-ok: env-knob-outside-config: deprecated spelling for directly-constructed executors
                    "1", "true", "yes",
                )
            cached = self._gram_env_cache = (
                bool(no_gram),
                self._gram_rows_max_cfg
                # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
                or int(os.environ.get("PILOSA_TPU_GRAM_ROWS_MAX", "4096")),  # analysis-ok: env-knob-outside-config: deprecated spelling for directly-constructed executors
            )
        return cached

    def _gram_rows_max(self) -> int:
        """Row ceiling for the cached-Gram strategy.  The chunked builder
        (bitwise.pair_gram) streams (slice, word-chunk) steps, so rows no
        longer bound the build transient; what remains is the Gram matrix
        itself — R^2 int32 on device, fetched once to host for the native
        lookup lane (pn_gram_counts).  4096 rows = a 64 MiB Gram; the
        pool HBM budget bounds build FLOPs (R * S*R * 2^20 MACs with
        S*R capped by PILOSA_TPU_POOL_BYTES) to a few MXU-seconds."""
        return self._gram_env()[1]

    def _gram_could_serve(self, n_rows: int, n_slices: int) -> bool:
        """Whether the cached-Gram strategy is ELIGIBLE for a working set
        of this size (same gates as _frame_gram, sans warmth): the
        row-major gather lane must never displace it — warm Gram serving
        is host-side lookups, strictly faster than any per-query kernel."""
        no_gram, rows_max = self._gram_env()
        if no_gram:
            return False
        from pilosa_tpu.ops.dispatch import _GRAM_SLICES_MAX

        bucket = 1 << max(0, n_rows - 1).bit_length()
        return bucket <= rows_max and n_slices <= _GRAM_SLICES_MAX

    def _frame_gram(self, matrix, box: Optional[dict]):
        """Cached all-pairs AND-count Gram for a fused-path row matrix.

        Computed lazily on the SECOND request against an unchanged cached
        matrix (cold single requests keep the cheaper direct kernels;
        steady-state dashboards upgrade to host-side count lookups, which
        answer every pair op via gram_pair_counts identities).  The box
        lives and dies with the cache entry, so any patch/append/rebuild
        invalidates the Gram with it.
        """
        if box is None or box.get("hits", 0) < 2:
            return None
        if self._gram_env()[0]:  # NO_GRAM
            return None
        gram = box.get("gram")
        if gram is not None:
            return gram
        shape = getattr(matrix, "shape", None)
        if not shape:
            return None
        # Pool matrices carry free capacity slots past n_used; the Gram
        # only needs the occupied slot range (power-of-two bucketed so the
        # matmul shape stays jit-stable).  Slot ids in id_pos are all
        # < n_used, so a gram over the truncated matrix answers every pair.
        n_used = box.get("n_used", shape[1])
        bucket = min(shape[1], 1 << max(0, (n_used - 1)).bit_length()) if n_used else 0
        if bucket == 0:
            return None
        # The chunked builder (bitwise.pair_gram) streams (slice,
        # word-chunk) steps, so only GRAM_STEP_BYTES of unpacked bits are
        # live per step regardless of row count; the gates left are the
        # Gram matrix size (rows) and the int32 count bound (slices).
        from pilosa_tpu.ops.dispatch import _GRAM_SLICES_MAX

        if bucket > self._gram_rows_max() or shape[0] > _GRAM_SLICES_MAX:
            return None
        mu = box.get("mu")
        if mu is None or not mu.acquire(blocking=False):
            # Another request is already building this Gram; serve this one
            # through the direct kernels instead of piling up builders.
            return None
        try:
            gram = box.get("gram")
            if gram is None:
                m = matrix if bucket == shape[1] else matrix[:, :bucket, :]
                if self.meter is not None:
                    with self.meter.measure("gram") as d:
                        gram = self.engine.pair_gram(m)
                        if gram is not None:
                            # The R^2 count matrix fetched to host.
                            d.add_bytes(int(gram.nbytes))
                else:
                    gram = self.engine.pair_gram(m)
                if gram is None:
                    box["hits"] = -(1 << 30)  # engine can't: stop re-checking
                    return None
                box["gram"] = gram
            return gram
        finally:
            mu.release()

    def _peek_pool_cap(
        self, index: str, frame: str, view: str, slices, lane: str = ""
    ) -> int:
        """A lane pool's row capacity WITHOUT instantiating it or touching
        the LRU order — lane-choice probes must never evict a warm pool
        (and its cached Gram) for a lane that may not even be taken."""
        key = (index, frame, view, tuple(slices), lane)
        with self._matrix_mu:
            pool = self._matrix_cache.get(key)
            if pool is not None:
                return pool.cap_max
        return DeviceRowPool.default_cap(len(slices), _WORDS)

    def _pool_for(
        self, index: str, frame: str, view: str, slices, lane: str = ""
    ) -> "DeviceRowPool":
        """The paged device row pool for one (frame, view, slice batch).

        Pools live in the same small LRU the old fixed matrices did; each
        is bounded by the PILOSA_TPU_POOL_BYTES HBM budget and pages rows
        in/out on demand (rowpool.DeviceRowPool) — the row-count ceiling
        of the old design is gone.  ``lane`` separates workloads with
        different paging patterns (TopN candidate streams vs fused count
        working sets vs the row-major gather lane) so one can't evict
        another's residency.  Lanes holding the same frame's rows each
        carry the per-pool budget: a frame whose workload mixes
        Gram-scale and gather-scale requests keeps both lanes warm (up
        to 2x one pool's budget for that frame), bounded overall by
        this LRU's entry count — the cost of never paging one workload
        class's residency out for the other's.
        """
        key = (index, frame, view, tuple(slices), lane)
        row_major = lane == "rmgather"
        with self._matrix_mu:
            pool = self._matrix_cache.get(key)
            if pool is None:

                def fetch(row_ids, slice_idxs, _key=key, _rm=row_major):
                    # Re-resolves fragments per fetch (they may be created
                    # by a first write after the pool exists).
                    idx_n, frame_n, view_n, slc, _lane = _key
                    return self._densify_block(
                        idx_n, frame_n, view_n,
                        [slc[si] for si in slice_idxs], row_ids, row_major=_rm,
                    )

                pool = DeviceRowPool(
                    self.engine, len(slices), _WORDS, fetch, row_major=row_major
                )
                self._matrix_cache[key] = pool
            self._matrix_cache.move_to_end(key)
            while len(self._matrix_cache) > self._matrix_cache_entries:
                self._matrix_cache.popitem(last=False)
        return pool

    def _frame_matrix(
        self, index: str, frame: str, slices, want: set[int],
        view: str = VIEW_STANDARD, lane: str = "",
    ) -> tuple[dict[int, int], object, Optional[dict]]:
        """Device row matrix holding (at least) ``want`` for a frame view.

        Pool-backed: rows page into HBM slots on demand and stay resident
        across requests; the returned id_pos maps every RESIDENT row to
        its slot in the returned (immutable) matrix snapshot.  Generations
        are read BEFORE acquire: a concurrent mutation mid-fetch can only
        make the recorded generations stale, forcing a refresh next
        request — never a stale hit.
        """
        frags = [self.holder.fragment(index, frame, view, s) for s in slices]
        gens = tuple(-1 if f is None else f.generation for f in frags)
        pool = self._pool_for(index, frame, view, slices, lane=lane)
        # Dirty-row delta for the pool's PATCH lane: when the fragment
        # journals can enumerate everything written since the pool's
        # recorded generations (and it fits the repair budget), acquire
        # rewrites just those rows and rank-k-repairs the Gram instead of
        # refreshing whole planes and resetting the box.  The unlocked
        # pool.gens read is benign: a stale (older) base only widens the
        # delta — a superset patch is still correct.
        dirty = None
        pool_gens = pool.gens
        if pool_gens is not None and pool_gens != gens:
            dirty = self._journal_dirty_rows(frags, pool_gens, gens)
        out = pool.acquire(sorted(want), gens, dirty_rows=dirty)
        if self.meter is not None:
            self._note_resident()
        return out

    def _note_resident(self) -> None:
        """Gauge the HBM-resident working set (engine.hbm_bytes): the
        pooled row matrices plus their cached Grams.  An estimate — a
        concurrent eviction between snapshot and sum is acceptable for
        a gauge."""
        from pilosa_tpu.engine import nbytes as _nbytes

        with self._matrix_mu:
            pools = list(self._matrix_cache.values()) + list(
                self._multi_matrix_cache.values()
            )
        total = 0
        for p in pools:
            m = getattr(p, "matrix", None)
            if m is None and isinstance(p, tuple):
                total += _nbytes(*[x for x in p if hasattr(x, "nbytes")])
                continue
            total += _nbytes(m)
            box = getattr(p, "box", None)
            if isinstance(box, dict):
                total += _nbytes(box.get("gram"))
        self.meter.resident(total)

    # -- call dispatch (executor.go:156-179) ------------------------------

    def _execute_call(self, index: str, c: pql.Call, slices, opt: ExecOptions) -> Any:
        if c.name == "Count":
            return self._execute_count(index, c, slices, opt)
        if c.name == "TopN":
            return self._execute_topn(index, c, slices, opt)
        if c.name == "SetBit":
            return self._execute_set_bit(index, c, opt)
        if c.name == "ClearBit":
            return self._execute_clear_bit(index, c, opt)
        if c.name == "SetRowAttrs":
            return self._execute_set_row_attrs(index, c, opt)
        if c.name in ("SetColumnAttrs", "SetProfileAttrs"):
            return self._execute_set_column_attrs(index, c, opt)
        if c.name in BITMAP_CALLS:
            return self._execute_bitmap_call(index, c, slices, opt)
        raise PilosaError(f"unknown call: {c.name}")

    # -- bitmap calls ------------------------------------------------------

    def _execute_bitmap_call(self, index: str, c: pql.Call, slices, opt: ExecOptions) -> QueryBitmap:
        def local_map(local_slices: list[int]) -> QueryBitmap:
            batch = self._eval_stack(index, c, local_slices)
            words = self.engine.to_numpy(batch)
            segs = {
                s: words[i]
                for i, s in enumerate(local_slices)
                if words[i].any()
            }
            return QueryBitmap(segs)

        result = self._map_reduce(
            index, c, slices, opt, local_map, lambda a, b: a.merge(b), QueryBitmap()
        )

        # Attach attributes at the coordinator (executor.go:166-177).
        if c.name == "Bitmap" and not opt.remote and not opt.exclude_attrs:
            idx = self.holder.index(index)
            frame = self.holder.frame(index, c.string_arg("frame") or DEFAULT_FRAME)
            if frame is not None:
                try:
                    row_id, row_ok = c.uint_arg(frame.row_label)
                    col_id, col_ok = c.uint_arg(idx.column_label)
                except TypeError:
                    row_ok = col_ok = False
                if row_ok:
                    result.attrs = frame.row_attr_store.attrs(row_id) or {}
                elif col_ok:
                    result.attrs = idx.column_attr_store.attrs(col_id) or {}
        return result

    def _eval_stack(self, index: str, c: pql.Call, slices: list[int]):
        """Evaluate a bitmap call tree to an engine batch uint32[k, W]."""
        if c.name == "Bitmap":
            return self._eval_bitmap_leaf(index, c, slices)
        if c.name == "Range":
            return self._eval_range(index, c, slices)
        children = [self._eval_stack(index, ch, slices) for ch in c.children]
        if c.name == "Intersect":
            if not children:
                raise PilosaError("empty Intersect query is currently not supported")
            out = children[0]
            for ch in children[1:]:
                out = self.engine.bit_and(out, ch)
            return out
        if c.name == "Union":
            if not children:
                return self.engine.asarray(np.zeros((len(slices), _WORDS), dtype=np.uint32))
            out = children[0]
            for ch in children[1:]:
                out = self.engine.bit_or(out, ch)
            return out
        if c.name == "Difference":
            if not children:
                raise PilosaError("empty Difference query is currently not supported")
            out = children[0]
            for ch in children[1:]:
                out = self.engine.bit_andnot(out, ch)
            return out
        if c.name == "Xor":
            if not children:
                raise PilosaError("empty Xor query is currently not supported")
            out = children[0]
            for ch in children[1:]:
                out = self.engine.bit_xor(out, ch)
            return out
        raise PilosaError(f"unknown bitmap call: {c.name}")

    def _resolve_bitmap_leaf(self, index: str, c: pql.Call) -> tuple[str, str, int]:
        """(frame, view, id) for a Bitmap() leaf (executor.go:428-473)."""
        idx = self.holder.index(index)
        if idx is None:
            raise ErrIndexNotFound(index)
        frame_name = c.string_arg("frame") or DEFAULT_FRAME
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            raise ErrFrameNotFound(frame_name)
        row_id, row_ok = c.uint_arg(frame.row_label)
        col_id, col_ok = c.uint_arg(idx.column_label)
        if row_ok and col_ok:
            raise PilosaError(
                f"Bitmap() cannot specify both {frame.row_label} and {idx.column_label} values"
            )
        if not row_ok and not col_ok:
            raise PilosaError(
                f"Bitmap() must specify either {frame.row_label} or {idx.column_label} values"
            )
        if col_ok:
            if not frame.inverse_enabled:
                raise ErrFrameInverseDisabled(
                    "Bitmap() cannot retrieve columns unless inverse storage enabled"
                )
            return frame_name, VIEW_INVERSE, col_id
        return frame_name, VIEW_STANDARD, row_id

    def _gather_rows(self, index: str, frame: str, view: str, row_id: int, slices: list[int]):
        rows = []
        zeros = None
        for s in slices:
            frag = self.holder.fragment(index, frame, view, s)
            if frag is None:
                if zeros is None:
                    zeros = self.engine.asarray(np.zeros(_WORDS, dtype=np.uint32))
                rows.append(zeros)
            else:
                # Device-cached row: hot rows stay resident in HBM across
                # queries instead of re-uploading every time.
                rows.append(frag.row_device(row_id, self.engine))
        return self.engine.stack_slices(rows)

    def _eval_bitmap_leaf(self, index: str, c: pql.Call, slices: list[int]):
        frame, view, id = self._resolve_bitmap_leaf(index, c)
        return self._gather_rows(index, frame, view, id, slices)

    def _parse_range_args(self, index: str, c: pql.Call):
        """(frame_name, frame, row_id, start, end) for a Range() call,
        with the sequential path's exact errors (executor.go:498-531)."""
        frame_name = c.string_arg("frame") or DEFAULT_FRAME
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            raise ErrFrameNotFound(frame_name)
        row_id, ok = c.uint_arg(frame.row_label)
        if not ok:
            raise PilosaError(f"Range() {frame.row_label} required")
        start_s = c.string_arg("start")
        end_s = c.string_arg("end")
        if not start_s:
            raise PilosaError("Range() start time required")
        if not end_s:
            raise PilosaError("Range() end time required")
        try:
            start = datetime.strptime(start_s, pql.TIME_FORMAT)
            end = datetime.strptime(end_s, pql.TIME_FORMAT)
        except ValueError:
            raise PilosaError("cannot parse Range() time")
        return frame_name, frame, row_id, start, end

    def _eval_range(self, index: str, c: pql.Call, slices: list[int]):
        """Range(): union of time-view rows covering [start, end)
        (executor.go:498-554)."""
        frame_name, frame, row_id, start, end = self._parse_range_args(index, c)
        out = self.engine.asarray(np.zeros((len(slices), _WORDS), dtype=np.uint32))
        if not frame.time_quantum:
            return out
        for view in tq.views_by_time_range(VIEW_STANDARD, start, end, frame.time_quantum):
            out = self.engine.bit_or(out, self._gather_rows(index, frame_name, view, row_id, slices))
        return out

    # -- Count (executor.go:576-605) ---------------------------------------

    def _execute_count(self, index: str, c: pql.Call, slices, opt: ExecOptions) -> int:
        if len(c.children) == 0:
            raise PilosaError("Count() requires an input bitmap")
        if len(c.children) > 1:
            raise PilosaError("Count() only accepts a single bitmap input")

        def local_map(local_slices: list[int]) -> int:
            batch = self._eval_stack(index, c.children[0], local_slices)
            return int(self.engine.count(batch).sum())

        return self._map_reduce(index, c, slices, opt, local_map, lambda a, b: a + b, 0)

    # -- TopN (executor.go:281-404) ----------------------------------------

    def _execute_topn(self, index: str, c: pql.Call, slices, opt: ExecOptions) -> list[cache_mod.Pair]:
        row_ids, _ = c.uint_slice_arg("ids")
        n, _ = c.uint_arg("n")
        pairs = self._execute_topn_slices(index, c, slices, opt)
        if not pairs or row_ids or opt.remote:
            return pairs
        # Phase 2: coordinator refetches exact counts for the merged id set
        # across all slices, then truncates (executor.go:299-317).
        other = c.clone()
        other.args["ids"] = sorted(p.id for p in pairs)
        trimmed = self._execute_topn_slices(index, other, slices, opt)
        if n:
            trimmed = trimmed[:n]
        return trimmed

    def _execute_topn_slices(self, index: str, c: pql.Call, slices, opt: ExecOptions) -> list[cache_mod.Pair]:
        def local_map(local_slices: list[int]) -> list[cache_mod.Pair]:
            return self._topn_local(index, c, local_slices)

        pairs = self._map_reduce(index, c, slices, opt, local_map, cache_mod.pairs_add, [])
        return cache_mod.pairs_sorted(pairs)

    def _topn_local(self, index: str, c: pql.Call, slices: list[int]) -> list[cache_mod.Pair]:
        frame_name = c.string_arg("frame") or DEFAULT_FRAME
        n, _ = c.uint_arg("n")
        field = c.string_arg("field")
        row_ids, _ = c.uint_slice_arg("ids")
        min_threshold, _ = c.uint_arg("threshold")
        filters = c.args.get("filters") or []
        tanimoto, _ = c.uint_arg("tanimotoThreshold")

        src_batch = None
        if c.children:
            if len(c.children) > 1:
                raise PilosaError("TopN() can only have one input bitmap")
            src_batch = self.engine.to_numpy(self._eval_stack(index, c.children[0], slices))

        scorer_for = self._topn_scorer_factory(index, frame_name, slices, src_batch)
        merged: list[cache_mod.Pair] = []
        for i, s in enumerate(slices):
            frag = self.holder.fragment(index, frame_name, VIEW_STANDARD, s)
            if frag is None:
                continue
            src_dense = src_batch[i] if src_batch is not None else None
            topt = TopOptions(
                n=int(n),
                src_dense=src_dense,
                scorer=scorer_for(i, src_dense),
                row_ids=row_ids,
                min_threshold=int(min_threshold),
                filter_field=field,
                filter_values=filters,
                tanimoto_threshold=int(tanimoto),
            )
            merged = cache_mod.pairs_add(merged, frag.top(topt))
        return merged

    def _topn_scorer_factory(self, index, frame_name, slices, src_batch):
        """Per-slice engine-backed |row & src| scorers for TopN candidates.

        The reference scores candidates with a per-row scalar loop
        (fragment.go:553-560); here each candidate chunk is one fused
        device dispatch against a paged device row pool.  The pool lives
        on its OWN lane key ("topn") so streaming tens of thousands of
        candidates through HBM pages against the scorer's slots without
        evicting the fused Count lane's hot rows or its Gram.  Chunks are
        padded to the fragment scoring chunk so jitted shapes never vary.
        Unbounded candidate sets just page (rank-cache scale included);
        the only host fallback left is an engine that can't score rows
        (numpy: the fragment's host path is the same math without an
        engine round trip) or a pool too small for even one chunk.
        """
        if (
            src_batch is None
            or self.engine.name == "numpy"
            or not getattr(self.engine, "supports_row_scorer", True)
        ):
            return lambda si, src_dense: None
        from pilosa_tpu.core.fragment import TOPN_SCORE_CHUNK

        state = {"src_dev": {}}
        all_slices = list(slices)
        pool = self._pool_for(index, frame_name, VIEW_STANDARD, all_slices, lane="topn")
        if pool.cap_max < TOPN_SCORE_CHUNK:
            return lambda si, src_dense: None  # can't hold one chunk

        if getattr(self.engine, "row_scorer_all_slices", False):
            return self._topn_scorer_factory_all_slices(
                index, frame_name, all_slices, src_batch, pool
            )

        def scorer_for(si: int, src_dense):
            if src_dense is None:
                return None

            def score(ids):
                matrix, pos = self._topn_acquire_pos(
                    index, frame_name, all_slices, pool, ids
                )
                src_dev = state["src_dev"].get(si)
                if src_dev is None:
                    # Tiled to match rows sliced from the 4D pool matrix.
                    tile = getattr(self.engine, "tile_src", self.engine.asarray)
                    src_dev = state["src_dev"][si] = tile(src_dense)
                rows = matrix[si][pos]
                counts = self.engine.batch_intersection_count(
                    rows, src_dev, tiled=getattr(matrix, "ndim", 3) == 4
                )
                return counts[: len(ids)]

            return score

        return scorer_for

    def _topn_acquire_pos(self, index, frame_name, all_slices, pool, ids):
        """Shared scorer helper: page the candidate rows into the pool
        and map ids to matrix slots, padded to TOPN_SCORE_CHUNK so the
        jitted scorer shapes never vary (pad scores are discarded)."""
        from pilosa_tpu.core.fragment import TOPN_SCORE_CHUNK

        frags = [
            self.holder.fragment(index, frame_name, VIEW_STANDARD, s)
            for s in all_slices
        ]
        gens = tuple(-1 if f is None else f.generation for f in frags)
        id_pos, matrix, _ = pool.acquire(sorted(set(ids)), gens)
        n = len(ids)
        padded = (
            list(ids) + [ids[0]] * (TOPN_SCORE_CHUNK - n)
            if n < TOPN_SCORE_CHUNK
            else list(ids)
        )
        pos = np.fromiter(
            (id_pos[i] for i in padded), dtype=np.int32, count=len(padded)
        )
        return matrix, pos

    def _topn_scorer_factory_all_slices(
        self, index, frame_name, all_slices, src_batch, pool
    ):
        """Hybrid memoizing scorer (round 5): phase-1 candidate chunks
        (each fragment's own rank-cache candidates, one consuming slice)
        dispatch just their slice; a candidate set re-asked by a SECOND
        slice (phase 2's merged-id refetch across every slice) upgrades
        to ONE all-slice launch (engine.topn_scorer_counts) memoized for
        the rest.  Multi-process meshes always use the SPMD all-slice
        dispatch (eager ``matrix[si]`` indexing would touch shards owned
        by other processes).  Falls back to the host loop for slice
        counts a mesh can't shard evenly."""
        n_dev = getattr(getattr(self.engine, "mesh", None), "n_devices", 1)
        if len(all_slices) % n_dev:
            return lambda si, src_dense: None
        # Single-slice dispatches are legal whenever every shard is
        # process-addressable (single-chip jax engines, single-process
        # meshes); multi-process meshes must always go through the SPMD
        # all-slice dispatch.
        single_ok = bool(getattr(self.engine, "supports_single_slice_score", True))
        state: dict = {"src_dev": None, "src_si": {}}

        def all_src_dev():
            if state["src_dev"] is None:
                src_stack = np.stack(
                    [np.asarray(src_batch[i]) for i in range(len(all_slices))]
                )
                state["src_dev"] = self.engine.prepare_topn_src(src_stack)
            return state["src_dev"]

        memo: dict = {}  # ids -> int[S, K] all-slice counts
        seen: dict = {}  # ids -> first slice position that scored them

        def acquire_pos(ids):
            return self._topn_acquire_pos(index, frame_name, all_slices, pool, ids)

        def scorer_for(si: int, src_dense):
            if src_dense is None:
                return None

            def score(ids):
                key = tuple(ids)
                counts = memo.get(key)
                if counts is not None:
                    return counts[si, : len(ids)]
                if single_ok and seen.setdefault(key, si) == si:
                    # First sight of this candidate set (phase 1: each
                    # fragment scores its OWN rank-cache candidates):
                    # dispatch just this slice — the all-slice launch
                    # would do S x the compute for one consumed row.
                    matrix, pos = acquire_pos(ids)
                    tile = getattr(self.engine, "tile_src", self.engine.asarray)
                    src_dev = state["src_si"].get(si)
                    if src_dev is None:
                        src_dev = state["src_si"][si] = tile(src_dense)
                    rows = matrix[si][pos]
                    c = self.engine.batch_intersection_count(
                        rows, src_dev, tiled=getattr(matrix, "ndim", 3) == 4
                    )
                    return c[: len(ids)]
                # A SECOND slice asking for the same ids (phase 2's
                # merged-id refetch re-queries every slice): one
                # all-slice dispatch, memoized for the rest.
                matrix, pos = acquire_pos(ids)
                counts = memo[key] = self.engine.topn_scorer_counts(
                    matrix, pos, all_src_dev()
                )
                return counts[si, : len(ids)]

            return score

        return scorer_for

    # -- writes (executor.go:702-805) --------------------------------------

    def _set_bit_args(self, index: str, c: pql.Call):
        idx = self.holder.index(index)
        if idx is None:
            raise ErrIndexNotFound(index)
        frame_name = c.string_arg("frame")
        if not frame_name:
            raise PilosaError(f"{c.name}() field 'frame' required")
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            raise ErrFrameNotFound(frame_name)
        row_id, ok = c.uint_arg(frame.row_label)
        if not ok:
            raise PilosaError(f"{c.name}() field '{frame.row_label}' required")
        col_id, ok = c.uint_arg(idx.column_label)
        if not ok:
            raise PilosaError(f"{c.name}() field '{idx.column_label}' required")
        timestamp = None
        ts = c.string_arg("timestamp")
        if ts:
            timestamp = datetime.strptime(ts, pql.TIME_FORMAT)
        return frame, row_id, col_id, timestamp

    def _execute_set_bit(self, index: str, c: pql.Call, opt: ExecOptions) -> bool:
        return self._execute_bit_write(index, c, opt, clear=False)

    def _execute_clear_bit(self, index: str, c: pql.Call, opt: ExecOptions) -> bool:
        return self._execute_bit_write(index, c, opt, clear=True)

    def _execute_bit_write(self, index: str, c: pql.Call, opt: ExecOptions, clear: bool) -> bool:
        """Write a bit on every owner of its slice — locally only when this
        node is an owner, forwarding to the others (executor.go:675-698,
        780-805).  A forwarded call (opt.remote) only writes locally."""
        frame, row_id, col_id, timestamp = self._set_bit_args(index, c)

        def write_local() -> bool:
            if clear:
                changed = frame.clear_bit(VIEW_STANDARD, row_id, col_id)
                if frame.inverse_enabled and frame.clear_bit(VIEW_INVERSE, col_id, row_id):
                    changed = True
            else:
                changed = frame.set_bit(VIEW_STANDARD, row_id, col_id, timestamp)
                if frame.inverse_enabled and frame.set_bit(VIEW_INVERSE, col_id, row_id, timestamp):
                    changed = True
            if changed:
                self._note_dirty_rows(index, frame.name, (row_id,))
            return changed

        if opt.remote or self.cluster is None or self.client_factory is None:
            return write_local()

        changed = False
        slice_i = col_id // SLICE_WIDTH
        for node in self.cluster.fragment_nodes(index, slice_i):
            if node.host == self.host:
                if write_local():
                    changed = True
            else:
                client = self.client_factory(node.host)
                res = client.execute_remote(
                    index, pql.Query(calls=[c]), deadline=opt.deadline
                )
                if res and res[0]:
                    changed = True
        return changed

    # -- attrs (executor.go:808-1006) --------------------------------------

    def _execute_set_row_attrs(self, index: str, c: pql.Call, opt: ExecOptions) -> None:
        frame_name = c.string_arg("frame")
        if not frame_name:
            raise PilosaError("SetRowAttrs() frame required")
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            raise ErrFrameNotFound(frame_name)
        row_id, ok = c.uint_arg(frame.row_label)
        if not ok:
            raise PilosaError(f"SetRowAttrs() row field '{frame.row_label}' required")
        attrs = dict(c.args)
        attrs.pop("frame", None)
        attrs.pop(frame.row_label, None)
        frame.row_attr_store.set_attrs(row_id, attrs)
        if not opt.remote:
            self._broadcast_attrs(index, c)
        return None

    def _execute_set_column_attrs(self, index: str, c: pql.Call, opt: ExecOptions) -> None:
        idx = self.holder.index(index)
        if idx is None:
            raise ErrIndexNotFound(index)
        col_id, ok = c.uint_arg(idx.column_label)
        if not ok:
            raise PilosaError(f"SetColumnAttrs() field '{idx.column_label}' required")
        attrs = dict(c.args)
        attrs.pop(idx.column_label, None)
        attrs.pop("frame", None)
        idx.column_attr_store.set_attrs(col_id, attrs)
        if not opt.remote:
            self._broadcast_attrs(index, c)
        return None

    def _broadcast_attrs(self, index: str, c: pql.Call) -> None:
        """Attr writes go to every node (executor.go:845-861)."""
        if self.cluster is None or self.client_factory is None:
            return
        for node in self.cluster.nodes:
            if node.host == self.host:
                continue
            self.client_factory(node.host).execute_remote(index, pql.Query(calls=[c]))

    # -- mapReduce (executor.go:1115-1244) ----------------------------------

    def _map_reduce(
        self, index: str, c, slices, opt: ExecOptions, local_map, reduce_fn, zero,
        remote_map=None,
    ):
        """Fan the call out over slice owners and reduce.

        Local slices evaluate as ONE batched computation (local_map gets the
        whole list); remote nodes get the call forwarded once each with
        their slice list, mirroring the reference's per-node batching.
        ``remote_map(client, node_slices)`` overrides how a remote node is
        driven (the fused batch path forwards a whole Query instead of one
        call).
        """
        slices = list(slices or [])

        def local_chunked(node_slices):
            # Slice-axis chunking for LOCAL evaluation: an index bigger
            # than device memory executes as a sequence of bounded slice
            # batches folded through reduce_fn (reduce identities hold:
            # int sum, segment merge, Pairs.Add are all zero-safe).  The
            # reference's per-slice goroutine loop has no size limit
            # either (executor.go:1115-1244); this is its bounded-memory
            # analog.
            chunk = self._slice_chunk_cfg
            if chunk <= 0:
                # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
                chunk = int(os.environ.get("PILOSA_TPU_SLICE_CHUNK", "2048"))  # analysis-ok: env-knob-outside-config: deprecated spelling for directly-constructed executors
            span = opt.span
            if len(node_slices) <= chunk:
                if span is None:
                    return local_map(node_slices)
                csp = span.child("slices")
                csp.tags["n"] = len(node_slices)
                try:
                    return local_map(node_slices)
                finally:
                    csp.finish()
            result = zero
            for i in range(0, len(node_slices), chunk):
                if opt.deadline is not None and i:
                    # Cancellation checkpoint between slice chunks: a
                    # bigger-than-memory scan stops streaming once the
                    # request's budget is gone.
                    opt.deadline.check("between slice chunks")
                csp = None
                if span is not None:
                    # One span per slice chunk: the streaming regime's
                    # per-chunk upload+dispatch time is exactly where
                    # big-index requests go slow.
                    csp = span.child("slice_chunk")
                    csp.tags["start"] = i
                    csp.tags["n"] = len(node_slices[i : i + chunk])
                result = reduce_fn(result, local_map(node_slices[i : i + chunk]))
                if csp is not None:
                    csp.finish()
            return result

        if self.cluster is None or opt.remote or self.client_factory is None:
            return reduce_fn(zero, local_chunked(slices))

        import concurrent.futures

        def run_node(node, node_slices):
            if node.host == self.host:
                return local_chunked(node_slices)
            client = self.client_factory(node.host)
            rsp = None
            if opt.span is not None:
                # Remote hop span: the client forwards the trace id in
                # X-Pilosa-Trace and grafts the peer's span tree (from
                # X-Pilosa-Trace-Spans) under this span, so the
                # coordinator's trace shows the remote node's stages.
                rsp = opt.span.child("remote")
                rsp.tags["host"] = node.host
                rsp.tags["slices"] = len(node_slices)
            try:
                if remote_map is not None:
                    return remote_map(client, node_slices, trace_span=rsp)
                # Conditional kwargs only when set: custom client factories
                # (tests, embedders) need not know the QoS/qcache kwargs.
                kw = {}
                if opt.deadline is not None:
                    kw["deadline"] = opt.deadline
                if opt.no_cache:
                    kw["no_cache"] = True
                if rsp is not None:
                    kw["trace_span"] = rsp
                return client.execute_remote_call(index, c, node_slices, **kw)
            finally:
                if rsp is not None:
                    rsp.finish()

        # Mid-query node-failure retry (executor.go:1147-1159): when a
        # remote node becomes UNREACHABLE (transport-level OSError — refused
        # connection, reset, timeout), its slices are re-mapped onto the
        # remaining replica owners and re-dispatched; the query only fails
        # once some slice has no live owner left.  Application errors from a
        # reachable node (and all local errors) are query errors and
        # propagate immediately — retrying them on replicas would just
        # repeat a deterministic failure and mask the real message.
        result = zero
        pending = slices
        failed_hosts: set[str] = set()
        last_failure: Optional[BaseException] = None
        while pending:
            try:
                by_node = self.cluster.slices_by_node(
                    index, pending, exclude_down=True, exclude_hosts=failed_hosts
                )
            except RuntimeError as e:
                raise PilosaError(str(e)) from last_failure
            pending = []
            with concurrent.futures.ThreadPoolExecutor(max_workers=max(1, len(by_node))) as pool:
                futs = {
                    pool.submit(run_node, node, node_slices): node
                    for node, node_slices in by_node.items()
                }
                for fut in concurrent.futures.as_completed(futs):
                    node = futs[fut]
                    try:
                        node_result = fut.result()
                    except OSError as e:
                        if node.host == self.host:
                            raise
                        last_failure = e
                        failed_hosts.add(node.host)
                        pending.extend(by_node[node])
                        continue
                    result = reduce_fn(result, node_result)
        return result
