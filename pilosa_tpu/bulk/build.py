"""Sort/segment/scatter bulk build kernels.

A bulk chunk is two uint64 columns (row ids, global column ids).  The
build turns them into packed-uint32 word planes — one ``uint32[W]``
plane per touched (slice, row), ``W = SLICE_WIDTH // 32`` — which is
EXACTLY the engine's HBM row layout, so a committed plane needs no
further transformation to serve.

Three stages, shared by both lanes:

1. **sort** — order pairs by (slice, row, local column);
2. **segment** — find the (slice, row) group boundaries (the group
   table is what the fragment commit keys on) and drop duplicate
   positions;
3. **scatter** — OR each position's bit into its group's word plane.

:func:`build_planes_numpy` is the host twin (vectorized lexsort +
``bitwise_or.reduceat``); :func:`build_planes_jax` runs the
sort/segment/scatter on device under ``jax.jit`` with padded shapes
(deduped positions make scatter-add equal scatter-or, which XLA lacks
natively).  Both return identical planes for identical input — the
differential suite in tests/test_bulk.py holds them to it.
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.pilosa import SLICE_WIDTH

# Words per (slice, row) plane: the packed-uint32 device row layout.
WORDS_PER_PLANE = SLICE_WIDTH // 32


def group_pairs(rows, cols):
    """Sort + segment: order (row, col) pairs by (slice, row, local) and
    return the group table.

    Returns ``(slice_ids i64[G], row_ids i64[G], gid_sorted i64[N],
    local_sorted i64[N])`` where ``gid_sorted`` maps each sorted pair to
    its dense (slice, row) group and ``local_sorted`` is its in-slice
    column.  The sorted order makes every downstream flat index
    nondecreasing, which is what both scatter lanes lean on.
    """
    rows = np.asarray(rows, dtype=np.uint64)
    cols = np.asarray(cols, dtype=np.uint64)
    if len(rows) != len(cols):
        raise ValueError("row/col length mismatch")
    if len(rows) == 0:
        z = np.empty(0, dtype=np.int64)
        return z, z, z, z
    slices = (cols // np.uint64(SLICE_WIDTH)).astype(np.int64)
    local = (cols % np.uint64(SLICE_WIDTH)).astype(np.int64)
    r = rows.astype(np.int64)
    # The sort is the whole kernel's hot spot.  When (slice, row) fit
    # beside the 20 local bits in one uint64 — every realistic shape;
    # slice and row ids past 2^22 apiece do not — pack the three keys
    # into ONE composite word and radix the VALUES (np.sort, no argsort,
    # no gather): ~20x over the three-pass lexsort on million-pair
    # chunks.  The decomposed fields are exactly the sorted columns.
    sb = int(slices.max()).bit_length()
    rb = int(r.max()).bit_length()
    if sb + rb <= 44:
        key = np.sort(
            (slices.astype(np.uint64) << np.uint64(rb + 20))
            | (r.astype(np.uint64) << np.uint64(20))
            | local.astype(np.uint64)
        )
        ll = (key & np.uint64(SLICE_WIDTH - 1)).astype(np.int64)
        rr = ((key >> np.uint64(20)) & np.uint64((1 << rb) - 1)).astype(
            np.int64
        )
        ss = (key >> np.uint64(rb + 20)).astype(np.int64)
    else:
        order = np.lexsort((local, r, slices))
        ss, rr, ll = slices[order], r[order], local[order]
    newgrp = np.empty(len(ss), dtype=bool)
    newgrp[0] = True
    newgrp[1:] = (ss[1:] != ss[:-1]) | (rr[1:] != rr[:-1])
    gid = np.cumsum(newgrp) - 1
    firsts = np.flatnonzero(newgrp)
    return ss[firsts], rr[firsts], gid, ll


def _nonzero_words(gid, local):
    """Segment+scatter core shared by both host lanes: the UNIQUE flat
    word indices (``gid * W + word``, ascending) and each word's OR'd
    bit value, from the sorted group/local columns."""
    flat = gid * WORDS_PER_PLANE + (local >> 5)
    val = (np.uint32(1) << (local & 31).astype(np.uint32)).astype(np.uint32)
    # ``flat`` is already nondecreasing (sorted by (slice, row, local)),
    # so the word boundaries are plain diffs — no np.unique re-sort.
    start = np.flatnonzero(
        np.concatenate([np.ones(1, dtype=bool), flat[1:] != flat[:-1]])
    )
    return flat[start], np.bitwise_or.reduceat(val, start)


def build_planes_numpy(rows, cols):
    """Host build twin: ``(slice_ids, row_ids, planes uint32[G, W])``.

    ``bitwise_or.reduceat`` over the sorted flat word index does the
    segment+scatter in two vectorized passes (duplicate positions OR
    harmlessly, so no explicit dedup pass is needed on host).
    """
    slice_ids, row_ids, gid, local = group_pairs(rows, cols)
    g = len(slice_ids)
    planes = np.zeros((g, WORDS_PER_PLANE), dtype=np.uint32)
    if g == 0:
        return slice_ids, row_ids, planes
    uf, orv = _nonzero_words(gid, local)
    planes[uf // WORDS_PER_PLANE, uf % WORDS_PER_PLANE] = orv
    return slice_ids, row_ids, planes


def build_words_numpy(rows, cols):
    """Sparse host lane: ``(slice_ids, row_ids, counts, word_idx,
    word_vals)`` — the SAME planes as :func:`build_planes_numpy`, in
    CSR form over their nonzero words (``counts[i]`` words belong to
    group ``i``; ``word_idx`` is each word's in-plane index, unique and
    ascending within a group; ``word_vals`` its OR'd uint32 value).

    This is what the commit path wants on host: a chunk's pairs touch
    a few hundred words per plane, so materializing (and then OR-ing)
    full 32768-word planes per chunk is almost all page traffic for
    zeros.  ``Fragment.bulk_or_words`` scatters exactly these words
    into the persistent overlay instead.
    """
    slice_ids, row_ids, gid, local = group_pairs(rows, cols)
    if len(slice_ids) == 0:
        z = np.empty(0, dtype=np.int64)
        return slice_ids, row_ids, z, z, np.empty(0, dtype=np.uint32)
    uf, orv = _nonzero_words(gid, local)
    counts = np.bincount(uf // WORDS_PER_PLANE, minlength=len(slice_ids))
    return (slice_ids, row_ids, counts.astype(np.int64),
            uf % WORDS_PER_PLANE, orv)


def _pad_pow2(n: int, floor: int = 1024) -> int:
    """Next power-of-two bucket >= n (floor bounds jit recompiles)."""
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


from pilosa_tpu.analysis import lockcheck as _lockcheck

# Registered memo for the jitted pack kernel (jax.jit memoizes compiles
# per shape itself; this holds the single traced callable).
_JIT_CACHE = _lockcheck.named_global("bulk.build.jit_kernel", max_entries=4)


def _jax_kernel(jnp, jax):
    """The jitted sort/segment/scatter body (one compile per padded
    (P, GW) bucket pair, memoized by jax.jit itself)."""

    def pack(pos, n_out):
        # sort: deduplicable global keys (gid * SLICE_WIDTH + local);
        # pad entries carry the sentinel n_out * 32 * SLICE_WIDTH-safe
        # key that lands on the scratch slot past the planes.
        pos = jnp.sort(pos)
        # segment: first occurrence of each key survives, duplicates
        # zero out — after which scatter-ADD is exactly scatter-OR.
        first = jnp.concatenate(
            [jnp.ones((1,), dtype=bool), pos[1:] != pos[:-1]]
        )
        gid = pos // SLICE_WIDTH
        local = pos % SLICE_WIDTH
        flat = gid * WORDS_PER_PLANE + (local >> 5)
        flat = jnp.where(first, flat, n_out)  # dup -> scratch slot
        flat = jnp.minimum(flat, n_out)  # sentinel pads -> scratch slot
        val = (jnp.uint32(1) << (local & 31).astype(jnp.uint32)).astype(
            jnp.uint32
        )
        # scatter: one segment-sum over the padded word arena.
        out = jnp.zeros(n_out + 1, dtype=jnp.uint32)
        return out.at[flat].add(val)[:n_out]

    return jax.jit(pack, static_argnums=(1,))


def build_planes_jax(rows, cols, jnp=None):
    """Device build lane: same contract as :func:`build_planes_numpy`,
    with the sort/segment/scatter running under ``jax.jit`` on padded
    power-of-two shapes (stable compile buckets).  The group table is
    computed on host (the fragment commit needs host ids regardless);
    the bit data itself sorts, dedups, and scatters on device.
    """
    import jax

    if jnp is None:
        import jax.numpy as jnp_mod

        jnp = jnp_mod
    slice_ids, row_ids, gid, local = group_pairs(rows, cols)
    g = len(slice_ids)
    if g == 0:
        return slice_ids, row_ids, np.zeros((0, WORDS_PER_PLANE), np.uint32)
    kern = _JIT_CACHE.get("pack")
    if kern is None:
        kern = _jax_kernel(jnp, jax)  # tracing outside any lock
        _JIT_CACHE.put("pack", kern)
    pos = gid * SLICE_WIDTH + local  # int64, monotone-safe (< 2^63)
    p = _pad_pow2(len(pos))
    gp = _pad_pow2(g, floor=1)
    n_out = gp * WORDS_PER_PLANE
    padded = np.full(p, n_out * 32, dtype=np.int64)  # past every real key
    padded[: len(pos)] = pos
    words = kern(jnp.asarray(padded), n_out)
    planes = np.asarray(words).reshape(gp, WORDS_PER_PLANE)[:g]
    return slice_ids, row_ids, np.ascontiguousarray(planes)


def plane_positions(words: np.ndarray, base: int = 0) -> np.ndarray:
    """Set-bit positions of a packed-uint32 plane (uint64, ascending),
    offset by ``base`` — the dense→roaring bridge used by overlay
    materialization and the Arrow egress (matches
    ``roaring.Bitmap.from_dense_words`` bit order).
    """
    words = np.ascontiguousarray(words, dtype=np.uint32)
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint64) + np.uint64(base)
