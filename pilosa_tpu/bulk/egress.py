"""Arrow-native egress: fragment contents as IPC record batches.

The symmetric door to the bulk ingress: ``GET /export?format=arrow``
streams a fragment's (row, col) pairs as an Arrow IPC stream whose
schema is EXACTLY what the ingress accepts (uint64 ``row``/``col``
columns), so an export→re-ingest round trip converges byte-identically
— positions come out sorted, the encoder is deterministic, and the
builder packs the same planes back.

The column arrays are built zero-copy where pyarrow allows it
(``pa.array`` adopts the numpy buffers); the positions themselves come
straight off the fragment's merged dense view — roaring containers are
NOT materialized for an egress read (``Fragment.export_pairs`` merges
the pending overlay planes in word space).
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.ingest import ARROW_CONTENT_TYPE, IngestError, arrow_available  # noqa: F401
from pilosa_tpu.pilosa import SLICE_WIDTH

# Rows per emitted record batch: bounds the peak batch allocation while
# keeping per-batch framing overhead negligible at export bandwidth.
EXPORT_BATCH_PAIRS = 1 << 18


def encode_arrow_pairs(rows: np.ndarray, cols: np.ndarray,
                       batch_pairs: int = EXPORT_BATCH_PAIRS) -> bytes:
    """Encode (row, col) uint64 columns as an Arrow IPC stream.

    Deterministic: fixed schema, fixed batch split, no metadata that
    varies per process — equal inputs encode to equal bytes (the
    round-trip property the bench asserts).  Raises
    :class:`IngestError` 415 when pyarrow is unavailable.
    """
    try:
        import pyarrow as pa
    except ImportError:
        raise IngestError(
            415, "arrow egress unavailable: pyarrow not importable on this server"
        )
    rows = np.ascontiguousarray(rows, dtype=np.uint64)
    cols = np.ascontiguousarray(cols, dtype=np.uint64)
    schema = pa.schema([("row", pa.uint64()), ("col", pa.uint64())])
    import io

    buf = io.BytesIO()
    with pa.ipc.new_stream(buf, schema) as writer:
        n = len(rows)
        for i in range(0, max(n, 1), batch_pairs):
            writer.write_batch(
                pa.record_batch(
                    [pa.array(rows[i : i + batch_pairs], type=pa.uint64()),
                     pa.array(cols[i : i + batch_pairs], type=pa.uint64())],
                    schema=schema,
                )
            )
            if n == 0:
                break
    return buf.getvalue()


def export_fragment_arrow(frag, stats=None) -> bytes:
    """One fragment as an Arrow IPC stream of global (row, col) pairs.

    Pairs come from the fragment's merged dense view (storage +
    pending bulk overlay) — an egress touch does NOT materialize
    roaring containers; that is the point of the columnar door.
    """
    rows, cols = frag.export_pairs()
    out = encode_arrow_pairs(rows, cols)
    if stats is not None:
        stats.count("bulk.export_pairs", int(len(rows)))
        stats.count("bulk.export_bytes", len(out))
    return out


def positions_to_pairs(positions: np.ndarray, slice_i: int):
    """Fragment-linear positions -> global (row, col) uint64 columns."""
    positions = np.asarray(positions, dtype=np.uint64)
    rows = positions // np.uint64(SLICE_WIDTH)
    cols = positions % np.uint64(SLICE_WIDTH) + np.uint64(slice_i * SLICE_WIDTH)
    return rows, cols
