"""Device-first bulk index construction + Arrow-native egress.

The write-path twin of the device-first read path: columnar (row, col)
batches arriving through the streaming chunk wire (``POST
/index/<i>/frame/<f>/bulk``) are bit-packed into packed-uint32 word
planes by a sort/segment/scatter build kernel (:mod:`bulk.build`;
jitted on the jax engines, numpy twin for parity) and committed into
each fragment's pending dense overlay — roaring containers and rank
caches materialize lazily on the first snapshot/sync/egress touch
(:mod:`bulk.lazy` tracks the debt).  The symmetric egress door
(``GET /export?format=arrow``, :mod:`bulk.egress`) streams fragment
contents as Arrow IPC record batches built zero-copy from the same
column layout the ingress accepts, so an export→re-ingest round trip
is byte-identical.
"""

from pilosa_tpu.bulk.build import (  # noqa: F401
    WORDS_PER_PLANE,
    build_planes_numpy,
    group_pairs,
    plane_positions,
)
from pilosa_tpu.bulk.ingress import apply_bulk, complete_bulk  # noqa: F401
from pilosa_tpu.bulk.lazy import LEDGER, MaterializationLedger  # noqa: F401
