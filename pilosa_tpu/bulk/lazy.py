"""Materialization ledger: the lazy half of the bulk build.

A bulk commit leaves each touched fragment with a pending dense
overlay (packed word planes) instead of roaring containers — serving
reads merge the overlay for free, but snapshot, sync, digest, and
roaring-shaped reads need real containers.  The ledger tracks which
fragments owe that conversion, so:

- any storage-shaped touch on a fragment pays its own debt right there
  (the fragment calls back into roaring conversion itself — the ledger
  just stops tracking it), and
- transfer completion can opportunistically drain debt oldest-first
  under a time budget (``[bulk] materialize-budget-ms``): small loads
  finish fully materialized, huge backfills stay lazy and pay on
  touch.

Fragments are held weakly: a deleted frame's debt disappears with its
fragments, never pinning storage.
"""

from __future__ import annotations

import time
import weakref

from pilosa_tpu.analysis import lockcheck


class MaterializationLedger:
    """Registry of fragments carrying unmaterialized bulk overlays."""

    def __init__(self, stats=None):
        from pilosa_tpu.stats import NOP_STATS

        self.stats = stats if stats is not None else NOP_STATS
        self._mu = lockcheck.named_lock("bulk.lazy._mu")
        # Insertion-ordered weak map: oldest debt first, so the budget
        # drain retires the fragments most likely to be touched next
        # (they have been lazy the longest).
        self._pending: "weakref.WeakValueDictionary[int, object]" = (
            weakref.WeakValueDictionary()
        )

    def note_pending(self, frag) -> None:
        """A bulk commit left ``frag`` with overlay debt."""
        with self._mu:
            self._pending[id(frag)] = frag
        self.stats.gauge("bulk.lazy_pending", len(self._pending))

    def note_materialized(self, frag) -> None:
        """``frag`` paid its debt (on touch or via the drain)."""
        with self._mu:
            self._pending.pop(id(frag), None)
        self.stats.count("bulk.materialized")
        self.stats.gauge("bulk.lazy_pending", len(self._pending))

    def pending_count(self) -> int:
        with self._mu:
            return len(self._pending)

    def materialize_some(self, budget_ms: float) -> int:
        """Drain overlay debt oldest-first until ``budget_ms`` is spent
        (<= 0 means fully lazy: drain nothing).  Returns the number of
        fragments materialized.  The budget is checked BETWEEN
        fragments — one fragment's conversion always completes once
        started (partial conversions would leave torn digests)."""
        if budget_ms <= 0:
            return 0
        t0 = time.perf_counter()
        done = 0
        while (time.perf_counter() - t0) * 1000.0 < budget_ms:
            with self._mu:
                frag = None
                for key in self._pending:
                    frag = self._pending.get(key)
                    if frag is not None:
                        break
            if frag is None:
                break
            # materialize_bulk unregisters via note_materialized; a
            # concurrent touch that beat us here makes this a no-op.
            frag.materialize_bulk()
            done += 1
        if done:
            self.stats.timing(
                "bulk.materialize_drain", time.perf_counter() - t0
            )
        return done


# Process-wide default ledger: fragments report overlay debt here, the
# bulk doors drain it under the configured budget.
LEDGER = MaterializationLedger()
