"""Bulk build commit lane: decoded columns -> fragment word planes.

This is the apply half of the device-first bulk door
(``POST /index/<i>/frame/<f>/bulk``).  One chunk's (row, col) columns
run through the engine's sort/segment/scatter build (bulk/build.py) —
on the jax engine the bit data sorts, dedups, and packs on device —
and the resulting planes commit into fragments per (view, slice) as a
pending dense overlay (``Fragment.bulk_set_planes``).  No roaring
container is touched here: containers and rank caches materialize
lazily (bulk/lazy.py) on the first snapshot/sync/digest/mutation
touch, or opportunistically at transfer completion under the
``[bulk] materialize-budget-ms`` budget.

Both front ends (the HTTP handler and the lockstep service) drive
these functions, so a lockstep deployment replays bulk chunks through
the control-plane total order with the same semantics as a plain
server.
"""

from __future__ import annotations

import time

import numpy as np

from pilosa_tpu.bulk.build import build_words_numpy
from pilosa_tpu.stats import NOP_STATS


def _commit_view(view, rows, cols, engine=None, batch_slices: int = 8,
                 deadline=None) -> int:
    """Build one view's orientation and commit it per slice.
    ``batch_slices`` bounds how many slice fragments commit between
    deadline checks (and how much transient build memory one iteration
    pins).  Returns the number of (slice, row) planes committed.

    Two commit lanes, same semantics: engines exposing ``build_words``
    (host/numpy) commit sparse — only each plane's touched words
    scatter into the overlay; engines whose scatter output is born
    dense on device (``build_planes``, the jax lanes) commit whole
    planes."""
    build_words = (
        getattr(engine, "build_words", None)
        if engine is not None else build_words_numpy
    )
    if build_words is not None:
        slice_ids, row_ids, counts, widx, wvals = build_words(rows, cols)
        offs = np.concatenate(
            [np.zeros(1, dtype=np.int64), np.cumsum(counts)]
        )
    else:
        slice_ids, row_ids, planes = engine.build_planes(rows, cols)
    if len(slice_ids) == 0:
        return 0
    # group_pairs orders groups by (slice, row): one boundary scan
    # yields each slice's contiguous plane block.
    uniq, starts = np.unique(slice_ids, return_index=True)
    bounds = list(starts.tolist()) + [len(slice_ids)]
    batch_slices = max(1, int(batch_slices))
    committed = 0
    for i, s in enumerate(uniq.tolist()):
        if deadline is not None and i % batch_slices == 0 and i:
            deadline.check("bulk commit")
        lo, hi = bounds[i], bounds[i + 1]
        frag = view.create_fragment_if_not_exists(int(s))
        if build_words is not None:
            committed += frag.bulk_or_words(
                row_ids[lo:hi], counts[lo:hi],
                widx[offs[lo]:offs[hi]], wvals[offs[lo]:offs[hi]],
            )
        else:
            committed += frag.bulk_set_planes(row_ids[lo:hi], planes[lo:hi])
    return committed


def apply_bulk(frame, rows, cols, engine=None, executor=None, index: str = "",
               deadline=None, batch_slices: int = 8, stats=None) -> int:
    """Apply one decoded bulk chunk: device build + overlay commit for
    the standard view (and the inverse view with the columns swapped,
    mirroring the streamed door's fan-out), executor dirty-row notes so
    warm serve state patches instead of rebuilding.  Returns the pair
    count applied (the overlay OR cannot know which bits were new — the
    changed count the streamed door reports — without a dense read per
    row, which would defeat the device-first build)."""
    stats = stats if stats is not None else NOP_STATS
    t0 = time.perf_counter()
    rows = np.asarray(rows, dtype=np.uint64)
    cols = np.asarray(cols, dtype=np.uint64)
    from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD

    std = frame.create_view_if_not_exists(VIEW_STANDARD)
    _commit_view(std, rows, cols, engine=engine, batch_slices=batch_slices,
                 deadline=deadline)
    if deadline is not None:
        deadline.check("bulk apply")
    if frame.inverse_enabled:
        inv = frame.create_view_if_not_exists(VIEW_INVERSE)
        _commit_view(inv, cols, rows, engine=engine,
                     batch_slices=batch_slices, deadline=deadline)
    if executor is not None and len(rows):
        executor.note_external_write(
            index, frame.name, np.unique(rows).tolist()
        )
    stats.count("bulk.pairs", int(len(rows)))
    stats.timing("bulk.build", time.perf_counter() - t0)
    return int(len(rows))


def complete_bulk(frame, budget_ms: float = 0.0) -> None:
    """Transfer-completion hook: rank caches fresh NOW (import parity —
    the rankings seed from merged overlay counts, still lazily), then
    an opportunistic overlay->roaring drain under ``budget_ms`` (0 =
    stay fully lazy)."""
    from pilosa_tpu.bulk.lazy import LEDGER
    from pilosa_tpu.ingest import recalc_frame_caches

    recalc_frame_caches(frame)
    LEDGER.materialize_some(budget_ms)
