"""Generation-keyed query result cache: exact whole-query memoization.

No reference analog — the reference re-executes every PQL request from
scratch.  Production bitmap-index traffic is heavily skewed toward
repeated queries (the same dashboards and segments hit over and over),
and the Roaring line of work wins precisely by never recomputing what
set algebra already knows; this subsystem applies the same principle
one level up, at whole-query granularity, in front of the executor.

Design:

- **Key**: canonical fingerprint of the parsed PQL call tree (the
  deterministic ``str(Query)`` rendering, memoized per raw request
  string) + the target index + the explicit slice set, so formatting
  variants of the same call tree share one entry and per-node remote
  sub-requests (``slices=[...]``) never collide with coordinator
  requests.
- **Validity**: the fragment *generation vector* the execution could
  have touched — every (view, slice) fragment generation of every
  frame the call tree references, plus the index/frame schema header
  (max slice, labels, time quantum).  Fragment generations come from a
  process-global counter bumped inside the fragment's own locked
  mutation methods, so ANY writer (executor paths, imports, restores,
  anti-entropy sync) invalidates matching entries with zero explicit
  invalidation traffic, and a deleted+recreated fragment can never
  revive an old entry (the counter never repeats).  The vector is
  snapshotted BEFORE execution and re-checked at store time: a write
  landing mid-execution skips the store rather than stamping post-write
  tokens onto possibly pre-write results (the same rule as the
  executor's serve-state capture).
- **Store**: byte-accounted LRU with cost-aware admission — only
  results whose measured execution cost clears ``min_cost_ms`` are
  admitted (cheap requests would pay more in cache bookkeeping than
  they save); errors are never cached (an exception never reaches the
  commit), and write-bearing or non-deterministic trees are never
  cached (see CACHEABLE_CALLS).

**What is cacheable**: every top-level call must be one of
``Count / Intersect / Union / Difference / Xor / Range``.  ``Bitmap``
is excluded at top level because it attaches row/column attributes,
which mutate without a generation bump (SetRowAttrs touches the attr
store only); ``TopN`` is excluded because its rank-cache ranking
recalculates on a time debounce, so a fresh execution may legitimately
differ without any write.  Bitmap leaves INSIDE set-op trees are fine —
only top-level Bitmap calls attach attrs.

**Multi-node clusters**: validity is judged against the LOCAL holder's
generation vector, but cluster writes are applied only on slice-owner
nodes (the coordinator forwards without a local write when it is not an
owner) — so a coordinator-scope result covering remotely-owned slices
could never be invalidated by those writes.  The executor therefore
caches only ``remote=True`` sub-requests when it has a cluster: those
execute purely over locally-owned slices, and every write to a locally
owned slice is applied locally on every owner, so local generations
fully cover them.  Coordinator-scope requests are counted ineligible
and always execute fresh (each peer's cached sub-answer still saves the
per-node work).

**Lockstep determinism**: hit/miss decisions depend only on replicated
state — the request strings (shipped in the batch entry), the mutation
order (the lockstep total order), and deterministic result sizes —
EXCEPT wall-clock cost admission, which is rank-local.  The lockstep
service therefore builds its cache with ``min_cost_ms=0`` (admit every
eligible read), making every decision a pure function of replicated
state: every rank hits or misses identically and no rank skips a
collective another rank runs (the same determinism rule as lockstep
error isolation and expired-request drops).
"""

from __future__ import annotations

import threading

from pilosa_tpu.analysis import lockcheck
import time
from collections import OrderedDict
from typing import Optional

# Per-request cache bypass header: the request neither reads nor stores
# a cache entry (A/B measurement, stale-read debugging).
NO_CACHE_HEADER = "X-Pilosa-No-Cache"

# Top-level call names whose results are pure functions of fragment
# contents (see module docstring for the Bitmap/TopN exclusions).
CACHEABLE_CALLS = frozenset(
    {"Count", "Intersect", "Union", "Difference", "Xor", "Range"}
)

# Call names that reference a frame (default frame when the arg is
# absent) anywhere in a tree.
_FRAME_CALLS = frozenset({"Bitmap", "Range", "TopN"})

DEFAULT_MAX_BYTES = 256 << 20
DEFAULT_MIN_COST_MS = 1.0

# Don't fingerprint megabyte request bodies (same bound as the parse
# cache): bulk-import-sized requests are never dashboard repeats.
_FINGERPRINT_MAX_LEN = 1 << 16


def referenced_frames(query) -> tuple:
    """Sorted tuple of frame names a parsed Query can touch."""
    from pilosa_tpu.executor import DEFAULT_FRAME

    frames: set = set()

    def walk(call):
        if call.name in _FRAME_CALLS or "frame" in call.args:
            frames.add(call.string_arg("frame") or DEFAULT_FRAME)
        for ch in call.children:
            walk(ch)

    for c in query.calls:
        walk(c)
    return tuple(sorted(frames))


def generation_vector(holder, index: str, frames: tuple) -> Optional[tuple]:
    """The validity token for one (index, frame set): the schema header
    plus every existing fragment's write generation across ALL views of
    each referenced frame (standard, inverse, and time views — a
    superset of what any one execution reads, so invalidation is
    conservative but exactness never depends on knowing the exact view
    cover).  None when the index is gone (nothing to validate against).
    """
    idx = holder.index(index)
    if idx is None:
        return None
    vec: list = [
        (idx.max_slice(), idx.max_inverse_slice(), idx.column_label, idx.time_quantum)
    ]
    for fname in frames:
        fr = holder.frame(index, fname)
        if fr is None:
            vec.append((fname, None))
            continue
        vec.append((fname, fr.row_label, fr.inverse_enabled, fr.time_quantum))
        # list() snapshots: schema merges / writes may insert views or
        # fragments concurrently — a racing insert at worst makes this
        # vector stale, which is a conservative miss, never a stale hit.
        for vname, view in sorted(list(fr.views.items()), key=lambda kv: kv[0]):
            for s, frag in sorted(list(view.fragments.items()), key=lambda kv: kv[0]):
                if frag is not None:
                    vec.append((vname, s, frag.generation))
    return tuple(vec)


def result_nbytes(results) -> int:
    """Byte-accounting estimate for one result list (duck-typed so this
    module never imports the executor)."""
    n = 512  # key + vector + entry overhead
    for r in results:
        segments = getattr(r, "segments", None)
        if segments is not None:  # QueryBitmap
            n += 128 + sum(
                int(getattr(seg, "nbytes", 64)) + 96 for seg in segments.values()
            )
        elif isinstance(r, list):  # TopN pairs (excluded today, sized anyway)
            n += 64 + 96 * len(r)
        else:  # counts / bools
            n += 48
    return n


class _Pending:
    """A cacheable miss in flight: key + pre-execution validity tokens.
    Returned by :meth:`QueryCache.lookup`, consumed by :meth:`commit`."""

    __slots__ = ("key", "index", "frames", "vec0", "t0")

    def __init__(self, key, index, frames, vec0, t0):
        self.key = key
        self.index = index
        self.frames = frames
        self.vec0 = vec0
        self.t0 = t0


class _Entry:
    __slots__ = ("index", "frames", "vec", "results", "nbytes", "tenant")

    def __init__(self, index, frames, vec, results, nbytes, tenant=None):
        self.index = index
        self.frames = frames
        self.vec = vec
        self.results = results
        self.nbytes = nbytes
        # Billing owner under multi-tenancy (index→tenant map), None
        # when tenancy is off.
        self.tenant = tenant


@lockcheck.guarded_class
class QueryCache:
    """The byte-accounted, generation-validated query result LRU.

    Thread-safe.  Counters (``hits / misses / bypasses / ineligible /
    evictions / stores`` and the ``bytes`` gauge) are exposed both as
    attributes (tests, bench) and through the optional stats client
    (``qcache.hit`` etc. at /debug/vars).  ``bypasses`` counts ONLY
    client-requested skips (X-Pilosa-No-Cache) so the A/B hit-rate
    denominator stays clean; writes, unparseable queries, and
    cluster-scope requests count as ``ineligible``.
    """

    # Lockset race detector declarations: the store/canon LRUs and the
    # byte/hit accounting all move under ``_mu`` — the request path is
    # every HTTP handler thread at once, and a lost `bytes -=` is a
    # permanently wrong eviction budget.
    _guarded_by_ = {
        "_store": "qcache._mu",
        "_canon": "qcache._mu",
        "bytes": "qcache._mu",
        "tenant_bytes": "qcache._mu",
        "hits": "qcache._mu",
        "misses": "qcache._mu",
        "bypasses": "qcache._mu",
        "ineligible": "qcache._mu",
        "evictions": "qcache._mu",
        "stores": "qcache._mu",
    }

    def __init__(
        self,
        max_bytes: int = DEFAULT_MAX_BYTES,
        min_cost_ms: float = DEFAULT_MIN_COST_MS,
        stats=None,
        clock=time.perf_counter,
        tenancy=None,
    ):
        from pilosa_tpu.stats import NOP_STATS

        self.max_bytes = int(max_bytes)
        self.min_cost_ms = float(min_cost_ms)
        # Adaptive admission floor (planner.AdaptiveBudgets): when the
        # server wires one, commit() derives the floor from the measured
        # cost distribution instead of the static min_cost_ms (which
        # stays the anchor the adaptive value is clamped around).  The
        # lockstep service NEVER sets this — its floor is forced to 0
        # for determinism and must not regrow from rank-local wall time.
        self.budgets = None
        self.stats = stats if stats is not None else NOP_STATS
        # TenancyState: per-tenant byte quotas ([tenancy] qcache-share).
        # Entries bill to the index's tenant; over-quota tenants reclaim
        # from THEMSELVES first, so one tenant's store flood can never
        # flush another tenant's working set.  None = no quotas.
        self.tenancy = tenancy
        self._clock = clock
        self._mu = lockcheck.named_lock("qcache._mu")
        self._store: "OrderedDict[tuple, _Entry]" = OrderedDict()
        # Raw request string -> (fingerprint, frames) for eligible
        # queries, or None for ineligible/unparseable ones; bounded LRU
        # so adversarial unique queries can't grow it without limit.
        self._canon: "OrderedDict[str, Optional[tuple]]" = OrderedDict()
        self._canon_max = 512
        self.bytes = 0
        # tenant -> resident bytes (entries removed at zero).
        self.tenant_bytes: dict = {}
        self.hits = 0
        self.misses = 0
        self.bypasses = 0
        self.ineligible = 0
        self.evictions = 0
        self.stores = 0

    # -- fingerprinting ---------------------------------------------------

    # Distinguishes "never memoized" from the memoized-None of an
    # ineligible query on the lock-free probe below.
    _CANON_MISS = object()

    def _canonical(self, query_str: str) -> Optional[tuple]:
        """(fingerprint, frames) for an eligible query string, None for
        write-bearing / non-cacheable / unparseable ones.  Memoized: the
        steady-state repeated request pays one dict lookup, not a parse
        + render.

        The hit probe is LOCK-FREE: memo values are immutable once
        stored (a tuple or None), so a concurrent insert/evict at worst
        misses and re-parses.  The trade is that a lock-free hit skips
        the LRU recency touch — a hot entry churned out by a flood of
        unique queries just re-parses and re-inserts itself.  All
        mutation stays under ``_mu`` (the lockset detector's contract
        for ``_canon``).
        """
        val = self._canon.get(query_str, self._CANON_MISS)
        if val is not self._CANON_MISS:
            return val
        info = None
        if len(query_str) <= _FINGERPRINT_MAX_LEN:
            from pilosa_tpu import pql

            try:
                q = pql.parse_cached(query_str)
            # analysis-ok: exception-hygiene: fingerprint probe; the normal execution path raises the real parse error
            except Exception:  # noqa: BLE001 — normal path raises the real error
                q = None
            if (
                q is not None
                and q.calls
                and all(c.name in CACHEABLE_CALLS for c in q.calls)
            ):
                info = (str(q), referenced_frames(q))
        with self._mu:
            self._canon[query_str] = info
            self._canon.move_to_end(query_str)
            while len(self._canon) > self._canon_max:
                self._canon.popitem(last=False)
        return info

    # -- the request path -------------------------------------------------

    def note_bypass(self) -> None:
        """A request that DECLINED the cache (X-Pilosa-No-Cache) —
        distinct from ineligible traffic so the A/B hit-rate denominator
        (hits / (hits + misses + bypasses)) measures only requests the
        cache could have served."""
        with self._mu:
            self.bypasses += 1
        self.stats.count("qcache.bypass")

    def note_ineligible(self) -> None:
        """A request the cache can never serve: a write-bearing or
        unparseable tree, or a cluster coordinator-scope request whose
        validity the local generation vector cannot cover."""
        with self._mu:
            self.ineligible += 1
        self.stats.count("qcache.ineligible")

    def lookup(self, holder, index: str, query_str: str, slices_key, remote: bool = False):
        """One request's cache probe.

        Returns ``(results, pending)``: a valid entry yields
        ``(list-copy of results, None)``; a cacheable miss yields
        ``(None, _Pending)`` for :meth:`commit` after execution; an
        ineligible request yields ``(None, None)`` and counts as
        ineligible (never a bypass — those are client-requested only).
        ``remote`` is part of the key: a remote-serving execution covers
        local slices only, never a coordinator's global answer (remote
        reads always carry explicit slices today — this keys the
        invariant rather than assuming it).
        """
        info = self._canonical(query_str)
        if info is None:
            self.note_ineligible()
            return None, None
        fp, frames = info
        key = (index, fp, slices_key, remote)
        # Lock-free probe: entries are immutable (_Entry is never
        # mutated after store) and the generation-vector re-check below
        # IS the validity gate, so reading a just-evicted or torn-LRU
        # view costs at most a spurious miss.  Store/evict (and the hit
        # accounting) stay under ``_mu``.
        entry = self._store.get(key)
        vec = generation_vector(holder, index, frames)
        if entry is not None:
            if vec is not None and vec == entry.vec:
                with self._mu:
                    if key in self._store:
                        self._store.move_to_end(key)
                    self.hits += 1
                self.stats.count("qcache.hit")
                return list(entry.results), None
            # Stale: a generation moved (or the schema did) — drop it
            # now rather than waiting for LRU churn.
            self._pop(key)
        with self._mu:
            self.misses += 1
        self.stats.count("qcache.miss")
        if vec is None:
            return None, None  # index missing: the execution will raise
        return None, _Pending(key, index, frames, vec, self._clock())

    def commit(self, holder, pending: _Pending, results) -> bool:
        """Admit one executed miss.  Declines when the measured cost is
        under ``min_cost_ms`` (not worth the bookkeeping) or a write
        landed mid-execution (the vector moved — storing would stamp
        pre-write results with post-write tokens).  Returns True when
        the entry was stored."""
        cost_ms = (self._clock() - pending.t0) * 1e3
        floor = (
            self.budgets.qcache_min_cost_ms()
            if self.budgets is not None
            else self.min_cost_ms
        )
        if cost_ms < floor:
            return False
        vec1 = generation_vector(holder, pending.index, pending.frames)
        if vec1 is None or vec1 != pending.vec0:
            return False
        nbytes = result_nbytes(results)
        if nbytes > self.max_bytes:
            return False
        tenant = (
            self.tenancy.tenant_of_index(pending.index)
            if self.tenancy is not None
            else None
        )
        entry = _Entry(
            pending.index, pending.frames, pending.vec0, list(results), nbytes,
            tenant=tenant,
        )
        with self._mu:
            old = self._store.pop(pending.key, None)
            if old is not None:
                self.bytes -= old.nbytes
                self._tenant_debit(old)
            self._store[pending.key] = entry
            self.bytes += nbytes
            if tenant is not None:
                self.tenant_bytes[tenant] = (
                    self.tenant_bytes.get(tenant, 0) + nbytes
                )
            self.stores += 1
            # Per-tenant quota: the committing tenant reclaims from its
            # OWN LRU entries first when it runs past its share, before
            # the global loop can touch anyone else's working set.
            if tenant is not None:
                quota = self.tenancy.qcache_quota(tenant, self.max_bytes)
                while quota > 0 and self.tenant_bytes.get(tenant, 0) > quota:
                    if not self._evict_tenant_locked(tenant):
                        break
            while self.bytes > self.max_bytes and self._store:
                # Under the global budget too, over-quota tenants pay
                # before anyone under quota loses an entry.
                if self.tenancy is not None and self._evict_over_quota_locked():
                    continue
                _, ev = self._store.popitem(last=False)
                self.bytes -= ev.nbytes
                self._tenant_debit(ev)
                self.evictions += 1
                self.stats.count("qcache.evict")
        self.stats.count("qcache.store")
        self.stats.gauge("qcache.bytes", self.bytes)
        return True

    def _tenant_debit(self, entry) -> None:
        """Return one removed entry's bytes to its tenant (``_mu``
        held by every caller)."""
        t = entry.tenant
        if t is None:
            return
        n = self.tenant_bytes.get(t, 0) - entry.nbytes  # analysis-ok: check-then-act: _mu held by every caller (commit/invalidate eviction paths); the _locked helper convention
        if n <= 0:
            self.tenant_bytes.pop(t, None)
        else:
            self.tenant_bytes[t] = n

    def _evict_tenant_locked(self, tenant) -> bool:
        """Evict ``tenant``'s least-recently-used entry (``_mu`` held).
        False when the tenant holds none."""
        for k, e in self._store.items():
            if e.tenant == tenant:
                self._store.pop(k)
                self.bytes -= e.nbytes  # analysis-ok: check-then-act: _mu held by every caller; the _locked helper convention
                self._tenant_debit(e)
                self.evictions += 1  # analysis-ok: check-then-act: _mu held by every caller; the _locked helper convention
                self.stats.count("qcache.evict")
                self.stats.count(f"tenancy.qcache_evict.{tenant}")
                return True
        return False

    def _evict_over_quota_locked(self) -> bool:
        """Evict the LRU entry of any tenant currently over its quota
        (``_mu`` held).  False when nobody is over."""
        for k, e in self._store.items():
            t = e.tenant
            if t is None:
                continue
            quota = self.tenancy.qcache_quota(t, self.max_bytes)
            if quota > 0 and self.tenant_bytes.get(t, 0) > quota:
                self._store.pop(k)
                self.bytes -= e.nbytes  # analysis-ok: check-then-act: _mu held by every caller; the _locked helper convention
                self._tenant_debit(e)
                self.evictions += 1  # analysis-ok: check-then-act: _mu held by every caller; the _locked helper convention
                self.stats.count("qcache.evict")
                self.stats.count(f"tenancy.qcache_evict.{t}")
                return True
        return False

    def tenant_bytes_snapshot(self) -> dict:
        """Per-tenant resident bytes (/debug/tenants)."""
        with self._mu:
            return dict(self.tenant_bytes)

    # -- invalidation hooks ------------------------------------------------

    def _pop(self, key) -> None:
        with self._mu:
            entry = self._store.pop(key, None)
            if entry is not None:
                self.bytes -= entry.nbytes
                self._tenant_debit(entry)
        self.stats.gauge("qcache.bytes", self.bytes)

    def purge_frame(self, index: str, frame: str) -> int:
        """Drop every entry that touches one (index, frame) — wired to
        frame deletion so a recreated namesake can never serve (or pin
        the memory of) the old frame's results.  Returns the count."""
        with self._mu:
            victims = [
                k
                for k, e in self._store.items()
                if e.index == index and frame in e.frames
            ]
            for k in victims:
                e = self._store.pop(k)
                self.bytes -= e.nbytes
                self._tenant_debit(e)
        if victims:
            self.stats.gauge("qcache.bytes", self.bytes)
        return len(victims)

    def purge_index(self, index: str) -> int:
        """Index-deletion analog of :meth:`purge_frame` (every frame)."""
        with self._mu:
            victims = [k for k, e in self._store.items() if e.index == index]
            for k in victims:
                e = self._store.pop(k)
                self.bytes -= e.nbytes
                self._tenant_debit(e)
        if victims:
            self.stats.gauge("qcache.bytes", self.bytes)
        return len(victims)

    def clear(self) -> None:
        with self._mu:
            self._store.clear()
            self.bytes = 0
            self.tenant_bytes.clear()
        self.stats.gauge("qcache.bytes", 0)

    def __len__(self) -> int:
        return len(self._store)


def from_env(min_cost_ms: Optional[float] = None, stats=None) -> Optional[QueryCache]:
    """Build a cache from ``PILOSA_TPU_QCACHE_*`` env, or None when not
    enabled — the default for directly-constructed executors, so
    embedders/tests/benches opt in explicitly (the server and CLI wire
    the ``[qcache]`` config instead).  ``min_cost_ms`` overrides the env
    (the lockstep service forces 0: wall-clock admission is rank-local,
    and a replicated decision needs a replicated input)."""
    import os

    if os.environ.get("PILOSA_TPU_QCACHE", "").lower() not in ("1", "true", "yes"):  # analysis-ok: env-knob-outside-config: from_env is the documented opt-in for direct embedders; the server wires [qcache] config
        return None
    max_bytes = int(os.environ.get("PILOSA_TPU_QCACHE_MAX_BYTES", str(DEFAULT_MAX_BYTES)))  # analysis-ok: env-knob-outside-config: from_env is the documented opt-in for direct embedders; the server wires [qcache] config
    if min_cost_ms is None:
        min_cost_ms = float(
            os.environ.get("PILOSA_TPU_QCACHE_MIN_COST_MS", str(DEFAULT_MIN_COST_MS))  # analysis-ok: env-knob-outside-config: from_env is the documented opt-in for direct embedders; the server wires [qcache] config
        )
    return QueryCache(max_bytes=max_bytes, min_cost_ms=min_cost_ms, stats=stats)
