"""Request-lifecycle QoS: deadlines, admission control, overload shedding.

No reference analog — handler.go serves every request it can accept()
and has no notion of a deadline or a full queue.  The north star
(heavy traffic from millions of users) needs the serving stack to
survive SATURATION: a request carries a deadline end to end (HTTP
header -> executor checkpoints -> cluster fan-out -> lockstep batch
entries), and every serving path has a bounded door — when the bound is
hit the request is rejected immediately (429 + Retry-After) instead of
queuing into collapse.

Pieces:

- :mod:`pilosa_tpu.qos.deadline` — ``Deadline`` (monotonic budget,
  header wire format) and ``DeadlineExceeded`` (HTTP 504);
- :mod:`pilosa_tpu.qos.admission` — request classes (read / write /
  admin), the per-class bounded admission gate, and ``ShedError``
  (HTTP 429/503 + Retry-After).
"""

from pilosa_tpu.qos.admission import (
    CLASS_ADMIN,
    CLASS_READ,
    CLASS_WRITE,
    AdmissionController,
    ShedError,
    classify_request,
)
from pilosa_tpu.qos.deadline import (
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceeded,
    deadline_from_headers,
)

__all__ = [
    "AdmissionController",
    "CLASS_ADMIN",
    "CLASS_READ",
    "CLASS_WRITE",
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "ShedError",
    "classify_request",
    "deadline_from_headers",
]
