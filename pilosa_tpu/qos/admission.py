"""Admission control: per-class bounded doors with shed-on-full.

Requests are classified into three classes — ``read`` (data-plane
queries, exports, fragment reads), ``write`` (imports, mutating PQL,
fragment restores), ``admin`` (schema, status, debug) — and each class
has a bounded door: at most ``depth`` requests executing, at most
``depth`` more waiting briefly (``queue-wait-ms``) for a slot.  Beyond
that the request is REJECTED AT THE DOOR with :class:`ShedError`
(HTTP 429 + ``Retry-After``) instead of queuing into collapse — under
overload the server keeps serving ``depth`` requests at pre-saturation
latency and sheds the excess, rather than serving everyone a timeout.

``depth <= 0`` disables the bound for that class (the pre-QoS
behavior, and the bench's QoS-off baseline).
"""

from __future__ import annotations

import threading

from pilosa_tpu.analysis import lockcheck
from contextlib import contextmanager
from typing import Optional

from pilosa_tpu.pilosa import PilosaError
from pilosa_tpu.pql.ast import WRITE_CALL_NAMES
from pilosa_tpu.stats import NOP_STATS

CLASS_READ = "read"
CLASS_WRITE = "write"
CLASS_ADMIN = "admin"
CLASSES = (CLASS_READ, CLASS_WRITE, CLASS_ADMIN)

# Mutating-call markers, matched as raw bytes so one scan classifies
# both JSON bodies (the PQL string itself) and protobuf QueryRequests
# (the PQL string is embedded verbatim as a length-delimited field).
_WRITE_MARKERS = tuple(f"{name}(".encode() for name in WRITE_CALL_NAMES)


class ShedError(PilosaError):
    """Request rejected at the door (HTTP 429, or 503 when the serving
    plane itself is down); ``retry_after`` is the client hint in
    seconds for the ``Retry-After`` header."""

    def __init__(self, message: str, retry_after: float = 0.25, status: int = 429):
        super().__init__(message)
        self.retry_after = retry_after
        self.status = status


def classify_request(method: str, path: str, body: bytes = b"") -> str:
    """Map (method, path, body) to an admission class.

    The query route is split by content: a request whose body carries a
    mutating call (SetBit & co.) is a write, everything else a read —
    a cheap substring scan, not a parse, so classification never fails
    a request and costs O(len(body)) at the door.
    """
    if path.startswith("/index/") and path.endswith("/query"):
        if any(m in body for m in _WRITE_MARKERS):
            return CLASS_WRITE
        return CLASS_READ
    if path == "/import" or (
        method == "POST"
        and (
            path in ("/fragment/data", "/fragment/block/diff")
            or path.endswith("/restore")
            or path.endswith("/ingest")
            or path.endswith("/bulk")
        )
    ):
        # /ingest and /bulk: the streamed and device-build columnar
        # ingest doors — writes, so the admission bound backpressures
        # each chunk and the replica router sequences + WAL-logs it
        # like any other write.
        return CLASS_WRITE
    if path == "/export" or path.startswith("/fragment/") or path.endswith("/attr/diff"):
        return CLASS_READ
    return CLASS_ADMIN


class AdmissionController:
    """Per-class bounded admission with a short in-door wait.

    A request ACQUIRES a slot for its class before executing and
    releases it after.  When all ``depth`` slots are busy the request
    waits at most ``queue_wait_ms`` (never past its deadline) for a
    release; when the wait lane itself is full (``depth`` waiters) it
    sheds immediately — the two bounds together cap the work the
    server ever holds to 2x depth per class.

    With a :class:`~pilosa_tpu.tenancy.TenancyState` attached AND a
    resolved tenant on the acquire, the same doors enforce weighted
    fair shares: a tenant past its weighted slice of ``depth`` waits or
    sheds while under-share tenants keep clearing, and the wait lane is
    bounded PER TENANT so a flooding tenant cannot fill it and shed a
    polite one at the door.  ``tenancy is None`` or ``tenant is None``
    takes the pre-tenancy path byte-identically.
    """

    def __init__(
        self,
        depths: Optional[dict[str, int]] = None,
        queue_wait_ms: float = 100.0,
        retry_after_ms: float = 250.0,
        stats=None,
        tenancy=None,
    ):
        self.depths = dict(depths or {})
        self.queue_wait_ms = queue_wait_ms
        self.retry_after = max(0.001, retry_after_ms / 1000.0)
        self.stats = stats if stats is not None else NOP_STATS
        self.tenancy = tenancy
        self._cv = lockcheck.named_condition("qos.admission._cv")
        self._active = {c: 0 for c in CLASSES}
        self._waiting = {c: 0 for c in CLASSES}
        # Totals (also mirrored into stats counters for /debug/vars).
        self.stat_admitted = 0
        self.stat_shed = 0

    def _shed(self, cls: str, tenant=None, fair=None) -> ShedError:
        self.stat_shed += 1
        self.stats.count(f"qos.shed.{cls}")
        if fair is not None and tenant is not None:
            fair.note_shed(cls, tenant)
            self.stats.count(f"tenancy.shed.{tenant}")
        return ShedError(
            f"{cls} admission queue full; retry after {self.retry_after:.3f}s",
            retry_after=self.retry_after,
        )

    def acquire(self, cls: str, deadline=None, tenant=None) -> None:
        depth = self.depths.get(cls, 0)
        fair = None
        if tenant is not None and self.tenancy is not None:
            fair = self.tenancy.fair
        with self._cv:
            if fair is not None:
                self._acquire_fair(fair, cls, depth, deadline, tenant)
                return
            if depth <= 0 or self._active[cls] < depth:
                self._active[cls] += 1
                self.stat_admitted += 1
                self.stats.gauge(f"qos.inflight.{cls}", self._active[cls])
                return
            if self._waiting[cls] >= depth:
                raise self._shed(cls)
            self._waiting[cls] += 1
            self.stats.gauge(f"qos.queue_depth.{cls}", self._waiting[cls])
            try:
                budget = self.queue_wait_ms / 1000.0
                if deadline is not None:
                    budget = min(budget, max(0.0, deadline.remaining_ms() / 1000.0))
                import time as _time

                end = _time.monotonic() + budget
                while self._active[cls] >= depth:
                    left = end - _time.monotonic()
                    if left <= 0:
                        raise self._shed(cls)
                    self._cv.wait(left)
            finally:
                self._waiting[cls] -= 1
                self.stats.gauge(f"qos.queue_depth.{cls}", self._waiting[cls])
            self._active[cls] += 1
            self.stat_admitted += 1
            self.stats.gauge(f"qos.inflight.{cls}", self._active[cls])

    def _acquire_fair(self, fair, cls: str, depth: int, deadline, tenant: str) -> None:
        """Fair-share acquire (``self._cv`` held).  Admission requires a
        free door slot AND the tenant under its weighted inflight cap;
        the wait lane is bounded per tenant (each tenant queues at most
        its own share of waiters) with a 2x-depth overall backstop."""
        if depth <= 0:
            # Unbounded door: nothing to share, account only.
            self._active[cls] += 1
            fair.note_admit(cls, tenant)
            self.stat_admitted += 1
            self.stats.count(f"tenancy.admit.{tenant}")
            self.stats.gauge(f"qos.inflight.{cls}", self._active[cls])
            return
        if self._active[cls] < depth and not fair.over_cap(cls, tenant, depth):
            self._active[cls] += 1
            fair.note_admit(cls, tenant)
            self.stat_admitted += 1
            self.stats.count(f"tenancy.admit.{tenant}")
            self.stats.gauge(f"qos.inflight.{cls}", self._active[cls])
            return
        if fair.wait_full(cls, tenant, depth) or self._waiting[cls] >= 2 * depth:
            raise self._shed(cls, tenant=tenant, fair=fair)
        self._waiting[cls] += 1
        fair.note_wait(cls, tenant, 1)
        self.stats.gauge(f"qos.queue_depth.{cls}", self._waiting[cls])
        try:
            budget = self.queue_wait_ms / 1000.0
            if deadline is not None:
                budget = min(budget, max(0.0, deadline.remaining_ms() / 1000.0))
            import time as _time

            end = _time.monotonic() + budget
            while self._active[cls] >= depth or fair.over_cap(cls, tenant, depth):
                left = end - _time.monotonic()
                if left <= 0:
                    raise self._shed(cls, tenant=tenant, fair=fair)
                self._cv.wait(left)
        finally:
            self._waiting[cls] -= 1
            fair.note_wait(cls, tenant, -1)
            self.stats.gauge(f"qos.queue_depth.{cls}", self._waiting[cls])
        self._active[cls] += 1
        fair.note_admit(cls, tenant)
        self.stat_admitted += 1
        self.stats.count(f"tenancy.admit.{tenant}")
        self.stats.gauge(f"qos.inflight.{cls}", self._active[cls])

    def release(self, cls: str, tenant=None) -> None:
        with self._cv:
            self._active[cls] -= 1
            if tenant is not None and self.tenancy is not None:
                self.tenancy.fair.note_release(cls, tenant)
                self.stats.gauge(f"qos.inflight.{cls}", self._active[cls])
                # Waiters have heterogeneous predicates (door slot AND
                # per-tenant cap), so a single notify could wake only an
                # over-cap tenant and strand an eligible one.
                self._cv.notify_all()
                return
            self.stats.gauge(f"qos.inflight.{cls}", self._active[cls])
            self._cv.notify()

    def tenants_snapshot(self) -> dict:
        """Per-tenant fair-share accounting rows (/debug/tenants)."""
        if self.tenancy is None:
            return {}
        with self._cv:
            return self.tenancy.fair.snapshot(self.depths)

    @contextmanager
    def admit(self, cls: str, deadline=None, tenant=None):
        self.acquire(cls, deadline, tenant=tenant)
        try:
            yield
        finally:
            self.release(cls, tenant=tenant)
