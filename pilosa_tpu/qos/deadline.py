"""Request deadlines: a monotonic time budget that rides the request.

A ``Deadline`` is created once at the door (from the
``X-Pilosa-Deadline-Ms`` header, the ``default-deadline-ms`` config, or
``PILOSA_TPU_DEADLINE_MS``) and threaded through handler -> executor ->
cluster fan-out.  Hops between machines forward the REMAINING budget in
milliseconds — never an absolute timestamp — so no clock sync is
assumed anywhere: each receiver re-anchors the budget against its own
monotonic clock.

Expiry surfaces as :class:`DeadlineExceeded` (HTTP 504), raised at
cheap CHECKPOINTS between units of work (between PQL calls, between
slice chunks in the fan-out) — an expired request stops occupying the
serve lane at the next checkpoint instead of running to completion.
"""

from __future__ import annotations

import time
from typing import Optional

from pilosa_tpu.pilosa import PilosaError

# Hop-by-hop wire format: remaining budget in integer milliseconds.
DEADLINE_HEADER = "X-Pilosa-Deadline-Ms"


class DeadlineExceeded(PilosaError):
    """The request's time budget ran out (HTTP 504).

    Deterministic given the same expiry decision — the lockstep service
    relies on this: rank 0 decides expiry once at ship time, the
    decision rides the batch entry, and every rank resolves the same
    requests to this same error.
    """

    def __init__(self, where: str = ""):
        suffix = f" ({where})" if where else ""
        super().__init__(f"deadline exceeded{suffix}")
        self.where = where


class Deadline:
    """A monotonic-clock deadline with an injectable clock (tests)."""

    __slots__ = ("_at", "_clock")

    def __init__(self, budget_ms: float, clock=time.monotonic):
        self._clock = clock
        self._at = clock() + max(0.0, float(budget_ms)) / 1000.0

    def remaining_ms(self) -> float:
        """Milliseconds left; <= 0 once expired."""
        return (self._at - self._clock()) * 1000.0

    def expired(self) -> bool:
        return self._clock() >= self._at

    def check(self, where: str = "") -> None:
        """Checkpoint: raise :class:`DeadlineExceeded` if expired."""
        if self.expired():
            raise DeadlineExceeded(where)

    def header_value(self) -> str:
        """Remaining budget for the next hop (floor 0: the receiver's
        door check sheds it immediately)."""
        return str(max(0, int(self.remaining_ms())))


def deadline_from_headers(headers, default_ms: float = 0.0) -> Optional[Deadline]:
    """Build the request's deadline from lowercase-keyed ``headers``.

    Header wins over ``default_ms`` (the server's configured default);
    ``None`` when neither applies — an unbounded request, the
    pre-QoS behavior.  A malformed header falls back to the default
    rather than failing the request at the door.
    """
    raw = (headers or {}).get(DEADLINE_HEADER.lower())
    if raw is not None:
        try:
            return Deadline(float(raw))
        except (TypeError, ValueError):
            pass
    if default_ms and default_ms > 0:
        return Deadline(default_ms)
    return None
