"""Replicated serving groups: read fan-out over a 2-D (slice x replica) mesh.

No single reference analog — the reference's ReplicaN (cluster.go:220-240)
replicates FRAGMENTS across ring nodes inside one cluster and lets the
executor pick any owner at query time (executor.go:1147-1159).  Here the
unit of replication is a whole SERVING GROUP: each group is a full
LockstepService-style unit (or a plain Server on dev rigs) owning a
complete copy of every slice, and a front-end ROUTER fans reads across
groups — read QPS grows with group count while one lockstep group's
semantics stay exactly what the stack already proved.

Pieces:

- :mod:`pilosa_tpu.replica.router` — :class:`ReplicaRouter`, the HTTP
  front door: classifies requests with the QoS classifier, routes READS
  to the least-inflight healthy group (one-shot failover to a sibling
  on connect/5xx failure), and ships WRITES total-ordered to ALL groups
  through one sequencer so every group's fragment generation vectors
  advance identically — which is what keeps each group's qcache and
  serve-state machinery read-your-writes correct with zero new
  invalidation traffic.
- :mod:`pilosa_tpu.replica.mesh` — device-mesh construction for the
  group's device plane: 2-D ``(slice, replica)`` via
  ``mesh_utils.create_hybrid_device_mesh`` when multihost (replica axis
  on DCN, slice collectives on ICI) with a flat single-process fallback
  so CPU/test environments run the same code.

GROUP IDENTITY: every serving group carries a ``group`` name and an
integer ``group epoch`` (bumped on each job restart).  The identity
rides every HTTP response as the ``X-Pilosa-Group: <name>@<epoch>``
header (the router records it and counts epoch bumps) and every
lockstep control-plane batch entry as a ``gepoch`` field (workers
fail-stop on a mismatch — a stale rank 0 from a previous incarnation
can never feed entries to restarted workers).  An epoch bump tells the
router the group's IN-MEMORY state (generation vectors, qcache) was
rebuilt from disk; nothing cross-group needs invalidating because no
cache entry ever crosses a group boundary.

DURABILITY & RECOVERY (PR 7): the router sequences every accepted
write into a WRITE-AHEAD LOG (:mod:`pilosa_tpu.replica.wal`) before
fan-out, commits on a DEGRADED QUORUM (majority of groups), and
re-converges down/lagging groups by streaming them the missed WAL
suffix (:mod:`pilosa_tpu.replica.catchup`) — a single dead group no
longer halts ingest cluster-wide.  Each group tracks and reports its
last-applied write sequence (``X-Pilosa-Applied-Seq`` beside
``X-Pilosa-Group``, plus the ``/replica/health`` JSON); only a fully
caught-up group serves reads.  Partial-failure orderings are
reproducible through the deterministic fault seam
(:mod:`pilosa_tpu.replica.faults`, ``PILOSA_TPU_FAULT_SPEC``).

RESYNC & ANTI-ENTROPY (PR 9): stale and blank groups SELF-HEAL — the
probe keeps visiting stale groups (at ``probe-max-interval``) and
drives an automated resync round (:mod:`pilosa_tpu.replica.resync`):
content-digest diff (:mod:`pilosa_tpu.replica.digest`, ``GET
/replica/digest``) against a healthy donor, differing fragments
streamed as serialized roaring payloads (chunked, CRC-framed,
resumable), applied-sequence seeded under the sequencer lock, WAL
catch-up for the final locked drain.  A background anti-entropy sweep
(``[replica] anti-entropy-interval``, off by default) compares healthy
groups' digests and repairs silent divergence from the majority copy
(``replica.divergence.<g>``).

Config: ``[replica] group / groups / router-port / failover /
probe-interval / probe-max-interval / wal-dir / wal-max-bytes /
anti-entropy-interval / resync-chunk-bytes`` TOML keys with
``PILOSA_TPU_REPLICA_*`` env overrides, wired through ``pilosa-tpu
replica-router`` and the lockstep CLI.
"""

from __future__ import annotations

# Response header carrying the serving group's identity ("name@epoch"):
# set by every group front door, read back by the router (epoch-bump
# detection) and by clients that want to know which replica answered.
GROUP_HEADER = "X-Pilosa-Group"

# Request header carrying the router-assigned WAL sequence number of a
# write (fan-out and catch-up replays alike); the group notes it as its
# applied high-water mark once the route answers deterministically.
WRITE_SEQ_HEADER = "X-Pilosa-Write-Seq"

# Response header: the group's last-applied write sequence, stamped
# beside X-Pilosa-Group on every response — the router's passive lag
# tracking (the /replica/health JSON carries the same number for the
# probe).
APPLIED_SEQ_HEADER = "X-Pilosa-Applied-Seq"

# Request header marking a catch-up replay (vs a live client write):
# groups tag sampled trace roots ``replay=true`` so replayed traffic is
# distinguishable at /debug/traces.
REPLAY_HEADER = "X-Pilosa-Replay"


def write_not_applied(status: int, retry_after=None) -> bool:
    """THE one predicate for "did this sequenced write LAND on the
    group?", shared by the router's write fan-out, the catch-up
    replay, and the group-side applied-mark bookkeeping so no path can
    disagree with another about a write's fate.  NOT applied: a 429,
    any 5xx, or any other answer carrying Retry-After (the admission
    door's shed shape even when the status is not 429) — all
    load/fault-dependent, so the write must stay replayable.  Applied:
    2xx, and deterministic 4xx (parse/schema errors answer identically
    on every group — replaying them only re-answers the same error)."""
    return status == 429 or status >= 500 or bool(retry_after)


def parse_group(spec: str) -> tuple[str, int]:
    """Split a ``name[@epoch]`` group identity; epoch defaults to 0."""
    spec = (spec or "").strip()
    name, _, epoch = spec.partition("@")
    try:
        return name, int(epoch or 0)
    except ValueError:
        return name, 0


def format_group(name: str, epoch: int = 0) -> str:
    return f"{name}@{int(epoch)}" if name else ""


def __getattr__(name):
    # PEP 562 lazy export: keep this package importable from the handler
    # and client modules without pulling the router's qos/trace imports
    # at module-import time (same contract as pilosa_tpu/parallel).
    if name in ("ReplicaRouter", "GroupState", "router_from_config"):
        from pilosa_tpu.replica import router as _router

        return getattr(_router, name)
    if name in ("WriteAheadLog", "WalRecord"):
        from pilosa_tpu.replica import wal as _wal

        return getattr(_wal, name)
    if name in ("AppliedSeq", "CatchupManager", "note_applied_from_headers"):
        from pilosa_tpu.replica import catchup as _catchup

        return getattr(_catchup, name)
    if name in ("FaultInjector", "FaultError", "InjectedStatus", "NOP_FAULTS"):
        from pilosa_tpu.replica import faults as _faults

        return getattr(_faults, name)
    if name in ("ResyncManager", "ResyncAbort", "ResyncUnsupported"):
        from pilosa_tpu.replica import resync as _resync

        return getattr(_resync, name)
    if name in ("holder_digest", "diff_digests", "majority_plan",
                "fragment_path", "parse_fragment_path"):
        from pilosa_tpu.replica import digest as _digest

        return getattr(_digest, name)
    if name == "build_group_mesh":
        from pilosa_tpu.replica.mesh import build_group_mesh

        return build_group_mesh
    if name in ("Shard", "ShardMap", "ShardMapError", "parse_shard_map",
                "single_shard_map", "uniform_shard_map"):
        from pilosa_tpu.replica import shards as _shards

        return getattr(_shards, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
