"""Automated group resync: stale and blank replica groups self-heal.

PR 7 left exactly one manual step in the failure-recovery story: a
group that lagged past ``wal-max-bytes`` was marked STALE and parked
for "operator resync", and a group started on a blank data dir could
only converge by replaying the entire write history bit by bit — if
the WAL even still held it.  This module closes both doors without a
human in the loop:

- DIGEST DIFF: the laggard's content digest (``GET /replica/digest``,
  replica/digest.py) is compared against a healthy caught-up DONOR
  group's; only the differing fragments move.
- FRAGMENT STREAM: each differing fragment ships as its serialized
  roaring payload (``GET /fragment/data`` off the donor, ``POST
  /fragment/import-roaring`` onto the laggard) — compressed container
  form, not bit-by-bit writes — in CRC-framed chunks.  A killed
  transfer RESUMES: the next round probes the laggard's staged offset
  and continues from there, and applying a payload twice is
  idempotent.
- SEED + HANDOFF: once the laggard's bytes match the donor's as of
  ``seed_seq`` (the donor's applied sequence captured BEFORE the
  digest fetch — writes landing during the stream may already be in
  the fetched bytes, and replaying them is the idempotent-re-apply
  contract), the laggard's ``AppliedSeq`` is seeded to ``seed_seq``
  under the router's sequencer lock (a bounded hold, like catch-up's
  locked drain) and the existing WAL catch-up replays the short
  remainder and flips the group back into rotation.  Rejoin therefore
  means *byte-identical + caught up*.  While a round runs, the
  router's WAL compaction is FLOORED at ``seed_seq`` so the handoff
  suffix stays replayable even for a stale group compaction would
  otherwise skip.

Failure is always safe: any aborted round (donor death mid-stream,
torn transfer, epoch bump on the laggard, seed refusal) leaves the
laggard out of rotation with whatever fragments already applied —
strictly closer to the donor — and the next probe retries.  A group
that does not speak the resync protocol (legacy build, lockstep front
end without the import lane) falls back to plain WAL replay when the
log still covers its gap.

The same fragment-stream path repairs DIVERGENCE found by the router's
anti-entropy sweep (router._anti_entropy_once): healthy groups' digests
are compared under the sequencer lock (a consistent cut — no write can
land between the fetches) and any mismatched fragment is repaired from
the majority copy (replica.digest.majority_plan).

Fault sites (replica/faults.py): ``resync.digest`` (digest fetch, key =
group), ``resync.fetch`` (donor fragment fetch, key = donor),
``resync.chunk`` (each chunk push, key = laggard), ``resync.seed``
(the seed-seq exchange, key = laggard) — so torn-transfer,
donor-death-mid-stream, and crash-before-seed orderings replay
deterministically in tier-1.
"""

from __future__ import annotations

import json
import time
import zlib
from typing import Optional

from pilosa_tpu.replica.digest import (
    diff_digests,
    fragment_query,
    parse_fragment_path,
)
from pilosa_tpu.stats import NOP_STATS


class ResyncAbort(Exception):
    """This resync round cannot finish (donor/laggard failure, epoch
    bump, refused chunk); the group stays out of rotation and the next
    probe retries."""


class ResyncUnsupported(ResyncAbort):
    """The laggard does not implement the resync wire protocol (404/405
    on the digest or import endpoints) — fall back to WAL replay when
    the log still covers its gap."""


class ResyncManager:
    """Drives fragment-level resync rounds for the router (probe thread)."""

    def __init__(self, router, wal, stats=None, chunk_bytes: int = 256 << 10,
                 locked_seed_s: float = 5.0, columnar: bool = False,
                 budgets=None):
        self.router = router
        self.wal = wal
        self.stats = stats if stats is not None else NOP_STATS
        # Chunk size of the fragment stream: small enough that a torn
        # transfer loses little, large enough that the per-chunk HTTP
        # round trip amortizes.
        self.chunk_bytes = max(1, chunk_bytes)
        # Adaptive chunk sizing (planner.AdaptiveBudgets): when the
        # router wires one, each push reads the chunk size from the
        # MEASURED stream bandwidth (fed back below) — fast links get
        # larger chunks, slow links keep resume granularity fine.  The
        # configured chunk_bytes stays the static fallback and anchor.
        self.budgets = budgets
        # Bound on the seed-seq exchange under the sequencer lock —
        # same rationale as CatchupManager.locked_drain_s: a laggard
        # that hangs mid-handoff must not stall every write.
        self.locked_seed_s = locked_seed_s
        # Columnar negotiation (PR-18 bulk wire): fragments the laggard
        # lacks ENTIRELY may move as Arrow record batches through its
        # device-build /bulk door — the bulk OR equals replacement only
        # over an empty target, so non-empty targets always take the
        # roaring byte stream.
        self.columnar = columnar

    # -- triggers ---------------------------------------------------------

    def covered(self, g) -> bool:
        """True when the WAL alone can converge ``g``: every live
        record in (applied, head] is still present (nothing it needs
        was compacted away)."""
        if self.wal.last_seq == 0 or g.applied_seq >= self.wal.last_seq:
            return True
        first = self.wal.first_seq
        return first != 0 and g.applied_seq + 1 >= first

    def needed(self, g) -> bool:
        """A probe answer that calls for a RESYNC round instead of
        plain catch-up: the group is stale (the WAL compacted past its
        lag), it reports ``applied_seq == 0`` over a non-empty sequence
        space (a blank data dir — streaming compressed fragments beats
        replaying the whole history write by write), or its gap is no
        longer covered by the log."""
        if g.stale:
            return True
        if self.wal.last_seq == 0:
            return False
        return g.applied_seq == 0 or not self.covered(g)

    # -- wire helpers -----------------------------------------------------

    def _check_epoch(self, g, start_epoch: Optional[str]) -> None:
        """Abort the round if the laggard restarted mid-round (its
        epoch header changed): a fresh incarnation must report its own
        state before absorbing a stream paced against its predecessor —
        the same guard catch-up applies per replayed record."""
        if (start_epoch is not None and g.epoch is not None
                and g.epoch != start_epoch):
            raise ResyncAbort(f"{g.name} restarted mid-resync ({g.epoch})")

    def _digest(self, g, site: str = "resync.digest") -> dict:
        self.router.faults.hit(site, key=g.name)
        status, _ct, payload, _h = self.router._forward(
            g, "GET", "/replica/digest", b"", {}, timeout_s=30.0
        )
        if status in (404, 405, 501):
            raise ResyncUnsupported(f"{g.name} serves no digest (HTTP {status})")
        if status != 200:
            raise ResyncAbort(f"digest fetch from {g.name}: HTTP {status}")
        try:
            return json.loads(payload)
        except ValueError:
            raise ResyncAbort(f"digest fetch from {g.name}: bad payload")

    def _pick_donor(self, exclude):
        """A healthy, caught-up, non-stale group to copy from: highest
        applied sequence wins, ties break to the smallest name (every
        round derives the same donor from the same table)."""
        live = [g for g in self.router._ready_groups() if g is not exclude]
        if not live:
            return None
        return min(live, key=lambda g: (-g.applied_seq, g.name))

    def _push_schema(self, donor_digest: dict, laggard_digest: dict, g,
                     start_epoch) -> None:
        """Create the indexes/frames the laggard is missing, with the
        donor's options (the import lane would create them with
        defaults — option parity matters for time quantum and cache
        shape).  Existing objects answer 409, which is fine."""
        have = {
            i.get("name"): {f.get("name") for f in i.get("frames", [])}
            for i in (laggard_digest.get("schema") or [])
        }
        for idx in donor_digest.get("schema") or []:
            name = idx.get("name")
            if name not in have:
                body = json.dumps({"options": {
                    "columnLabel": idx.get("columnLabel", ""),
                    "timeQuantum": idx.get("timeQuantum", ""),
                }}).encode()
                self._push(g, "POST", f"/index/{name}", body, start_epoch)
            frames_have = have.get(name, set())
            for fr in idx.get("frames", []):
                if fr.get("name") in frames_have:
                    continue
                body = json.dumps({"options": {
                    "rowLabel": fr.get("rowLabel", ""),
                    "inverseEnabled": fr.get("inverseEnabled", False),
                    "cacheType": fr.get("cacheType", ""),
                    "cacheSize": fr.get("cacheSize", 0),
                    "timeQuantum": fr.get("timeQuantum", ""),
                }}).encode()
                self._push(
                    g, "POST", f"/index/{name}/frame/{fr.get('name')}",
                    body, start_epoch,
                )

    def _push(self, g, method: str, path: str, body: bytes, start_epoch,
              ctype: str = "application/json",
              timeout_s: float = 30.0) -> tuple[int, bytes]:
        """One laggard exchange with the epoch guard applied."""
        headers = {"content-type": ctype} if body else {}
        status, _ct, payload, _rh = self.router._forward(
            g, method, path, body, headers, timeout_s=timeout_s
        )
        self._check_epoch(g, start_epoch)
        if status == 409:
            return status, payload  # caller-meaningful (resume / exists)
        if status in (404, 405, 501):
            raise ResyncUnsupported(f"{g.name} {method} {path}: HTTP {status}")
        if status >= 400:
            raise ResyncAbort(f"{g.name} {method} {path}: HTTP {status}")
        return status, payload

    # -- the fragment stream ----------------------------------------------

    def _stream_fragment_columnar(self, donor, g, path_key: str,
                                  start_epoch) -> Optional[int]:
        """Try the negotiated columnar move: fetch the donor fragment
        as Arrow record batches (``/export?format=arrow``) and push the
        stream through the laggard's device-build ``/bulk`` door in ONE
        CRC-framed chunk.  Returns bytes moved, or ``None`` when either
        side declines (no Arrow egress on the donor, no bulk door or
        chunk ceiling on the laggard) — the caller degrades to the
        roaring byte stream.  Only standard-view fragments the laggard
        LACKS are eligible: the bulk door ORs pairs in, which equals
        replacement only over an empty target (and feeds the inverse
        view itself, so inverse fragments never move columnar)."""
        index, frame, view, _slice_i = parse_fragment_path(path_key)
        if view != "standard":
            return None
        qs = fragment_query(path_key)
        self.router.faults.hit("resync.fetch", key=donor.name)
        status, _ct, data, _h = self.router._forward(
            donor, "GET", f"/export?{qs}&format=arrow", b"", {}, timeout_s=60.0
        )
        if status != 200 or not data:
            return None  # no Arrow egress (or empty): roaring path
        total, crc = len(data), zlib.crc32(data)
        base = (f"/index/{index}/frame/{frame}/bulk"
                f"?total={total}&crc={crc}&ccrc={crc}&off=0")
        self.router.faults.hit("resync.chunk", key=g.name)
        try:
            status, payload = self._push(
                g, "POST", base, data, start_epoch,
                ctype="application/vnd.apache.arrow.stream",
                timeout_s=120.0,
            )
        except ResyncAbort:
            # 404/405 (no bulk door), 413 (chunk ceiling), 415 (no
            # pyarrow on the laggard), ...: negotiate down, never
            # abort the round over the optional fast path.
            return None
        try:
            done = bool(json.loads(payload).get("done"))
        except (ValueError, TypeError):
            done = False
        if not done:
            return None
        self.stats.count("replica.resync_fragments")
        self.stats.count("replica.resync_columnar")
        return total

    def _stream_fragment(self, donor, g, path_key: str, start_epoch,
                         laggard_empty: bool = False) -> int:
        """Replace one fragment on ``g`` with the donor's serialized
        roaring payload — chunked, CRC-framed, resumable.  Returns the
        bytes actually pushed (a resumed transfer skips the staged
        prefix).  A donor 404 streams as a CLEAR (total=0): the donor
        no longer holds the fragment, so the laggard's copy empties.

        With columnar negotiation on and an empty target
        (``laggard_empty``), the Arrow fast path is tried first and any
        refusal degrades here."""
        if self.columnar and laggard_empty:
            moved = self._stream_fragment_columnar(
                donor, g, path_key, start_epoch
            )
            if moved is not None:
                return moved
            self.stats.count("replica.resync_columnar_fallback")
        qs = fragment_query(path_key)
        self.router.faults.hit("resync.fetch", key=donor.name)
        status, _ct, data, _h = self.router._forward(
            donor, "GET", f"/fragment/data?{qs}", b"", {}, timeout_s=60.0
        )
        if status == 404:
            data = b""
        elif status != 200:
            raise ResyncAbort(f"fragment fetch {path_key} from {donor.name}: "
                              f"HTTP {status}")
        total, crc = len(data), zlib.crc32(data)
        base = f"/fragment/import-roaring?{qs}&total={total}&crc={crc}"
        # Resume point: where does a previous (killed) transfer stand?
        self.router.faults.hit("resync.chunk", key=g.name)
        _st, payload = self._push(g, "POST", base + "&probe=1", b"", start_epoch)
        off = 0
        try:
            off = int(json.loads(payload).get("staged", 0))
        except (ValueError, TypeError):
            off = 0
        if not (0 <= off <= total):
            off = 0
        sent = 0
        while True:
            step = (
                self.budgets.resync_chunk_bytes()
                if self.budgets is not None
                else self.chunk_bytes
            )
            chunk = bytes(data[off : off + step])
            self.router.faults.hit("resync.chunk", key=g.name)
            t_push = time.perf_counter()
            status, payload = self._push(
                g, "POST", f"{base}&off={off}", chunk, start_epoch,
                ctype="application/octet-stream",
            )
            if self.budgets is not None and chunk:
                # Measured push bandwidth feeds the next chunk's sizing
                # (the "resync" budget lane).
                self.budgets.observe_transfer(
                    "resync", (time.perf_counter() - t_push) * 1e3, len(chunk)
                )
            if status == 409:
                # Offset disagreement: adopt the group's staged size
                # and resume (covers an idempotent re-send after a lost
                # response as well as a restarted transfer).
                try:
                    staged = int(json.loads(payload).get("staged", -1))
                except (ValueError, TypeError):
                    staged = -1
                if 0 <= staged <= total and staged != off:
                    off = staged
                    continue
                raise ResyncAbort(f"chunk at {off} refused by {g.name}: "
                                  f"{payload[:120]!r}")
            sent += len(chunk)
            off += len(chunk)
            try:
                applied = bool(json.loads(payload).get("applied"))
            except (ValueError, TypeError):
                applied = False
            if applied:
                self.stats.count("replica.resync_fragments")
                return sent
            if off >= total:
                raise ResyncAbort(
                    f"transfer of {path_key} to {g.name} completed without apply"
                )

    # -- suspect verification ---------------------------------------------

    def verify(self, g) -> bool:
        """Digest-check a SUSPECT group (it answered a write with a 4xx
        a sibling 2xx'd) against a healthy donor: equal digests clear
        the flag (a retried create legitimately 409s on the groups that
        already applied it); a mismatch drives a full resync round.
        Returns False when the check could not run — the next probe
        retries."""
        donor = self._pick_donor(g)
        if donor is None:
            return False
        try:
            equal = (
                self._digest(donor).get("digest")
                == self._digest(g).get("digest")
            )
        except (OSError, ResyncAbort):
            return False
        if equal:
            with self.router._mu:
                g.suspect = False
            self.stats.count("replica.suspect_cleared")
            return True
        self.stats.count(f"replica.divergence.{g.name}")
        if not self.resync(g):
            return False
        with self.router._mu:
            g.suspect = False
        return True

    # -- the resync round -------------------------------------------------

    def resync(self, g) -> bool:
        """One automated resync round for ``g`` (probe thread).  On
        success the group is byte-identical to the donor as of the seed
        sequence, fully caught up via WAL replay, and back in rotation;
        on any failure it stays out and the next probe retries."""
        router = self.router
        self.stats.count("replica.resync_rounds")
        t0 = time.perf_counter()
        start_epoch = g.epoch
        donor = self._pick_donor(g)
        if donor is None:
            # No healthy caught-up sibling to copy from; plain replay
            # can still finish a covered, non-stale gap.
            if not g.stale and self.covered(g):
                return router.catchup.catch_up(g)
            self.stats.count("replica.resync_abort")
            self.stats.set(
                "replica.last_failure", f"{g.name}: resync needs a donor group"
            )
            return False
        # Every write <= seed_seq is in the bytes we are about to copy
        # (captured BEFORE the digest); later writes may be too —
        # replaying them is the idempotent re-apply contract.
        seed_seq = donor.applied_seq
        # Floor compaction at the seed: the handoff suffix (seed_seq,
        # head] must stay replayable even though a stale g is excluded
        # from the usual min-applied watermark.
        with router._mu:
            router._resync_floor[g.name] = seed_seq
        try:
            donor_digest = self._digest(donor)
            laggard_digest = self._digest(g)
            self._check_epoch(g, start_epoch)
            plan = diff_digests(donor_digest, laggard_digest)
            self._push_schema(donor_digest, laggard_digest, g, start_epoch)
            for name in plan.drop_indexes:
                self._push(g, "DELETE", f"/index/{name}", b"", start_epoch)
            for index, frame in plan.drop_frames:
                self._push(
                    g, "DELETE", f"/index/{index}/frame/{frame}", b"", start_epoch
                )
            sent = 0
            l_frags = laggard_digest.get("fragments") or {}
            for path_key in plan.stream:
                sent += self._stream_fragment(
                    donor, g, path_key, start_epoch,
                    laggard_empty=path_key not in l_frags,
                )
            # SEED under the sequencer lock: no write can be sequenced
            # between "the bytes match seed_seq" and "the applied mark
            # says so", so catch-up's arithmetic is exact.  Bounded
            # hold (locked_seed_s) — a hanging laggard aborts the round
            # instead of stalling every write.
            with router._seq_mu:
                self.router.faults.hit("resync.seed", key=g.name)
                self._push(
                    g, "POST", "/replica/seed-seq",
                    json.dumps({"seq": seed_seq}).encode(), start_epoch,
                    timeout_s=self.locked_seed_s,
                )
                # The sequencer lock serializes the seed against new
                # writes, but applied_seq is TABLE state read by handler
                # threads — the mark itself moves under router._mu.
                from pilosa_tpu.analysis import spec

                with router._mu:
                    g.applied_seq = max(g.applied_seq, seed_seq)
                    spec.emit("seed", src=id(router.wal), group=g.name,
                              epoch=g.epoch, value=g.applied_seq)
            with router._mu:
                g.stale = False
            self.stats.count(f"replica.resync.{g.name}")
            if sent:
                self.stats.count("replica.resync_bytes", sent)
            self.stats.timing(
                "replica.resync_ms", (time.perf_counter() - t0) * 1e3
            )
        except ResyncUnsupported as e:
            # The group has no resync lane (legacy build / lockstep
            # front end): WAL replay still converges a covered gap.
            if not g.stale and self.covered(g):
                with router._mu:
                    router._resync_floor.pop(g.name, None)
                return router.catchup.catch_up(g)
            self.stats.count("replica.resync_abort")
            self.stats.set("replica.last_failure", f"{g.name}: {e}")
            return False
        except (OSError, ResyncAbort) as e:
            # Partial progress is safe progress: any fragment already
            # applied moved the laggard closer to the donor, its
            # applied mark did not move, and the next probe retries
            # (resuming mid-fragment from the staged offset).
            self.stats.count("replica.resync_abort")
            self.stats.set("replica.last_failure",
                           f"{g.name}: resync aborted: {e}")
            return False
        finally:
            with router._mu:
                router._resync_floor.pop(g.name, None)
        # Handoff: replay the (short) missed tail past seed_seq through
        # the normal catch-up, whose phase-2 locked drain flips the
        # group back into rotation.  g is no longer stale, so the
        # compaction watermark now includes it — the tail cannot vanish
        # between here and the drain.
        return router.catchup.catch_up(g)
