"""Replica digest protocol: a compact content fingerprint of one group.

The replica tier's convergence story (PRs 6-7) is ORDER-based: every
group applies the same total order of writes, so equal applied
sequences should mean equal bytes.  "Should" is not a verification —
an ambiguous 502 partial write replayed differently, a data dir
restored from an old backup, or a plain bug diverges a group silently,
and nothing notices until two replicas answer the same read
differently.  This module is the CONTENT half of convergence: each
group can be asked (``GET /replica/digest``, served by the HTTP
handler and the lockstep front end — rank 0 computes over replicated
state, so every rank agrees by construction) for a per-(index, frame,
view, slice) tree of fragment checksums plus the schema header, and
two groups holding identical logical bits produce byte-identical
digests regardless of the write path that built them (the reference's
holder syncer makes the same promise per fragment with its block
checksums, fragment.go:681-920 — this promotes it to whole groups).

Digest shape (JSON)::

    {
      "digest":    "<sha1 hex over schema + every fragment entry>",
      "schema":    [<holder.schema() — the index/frame option tree>],
      "fragments": {"<index>/<frame>/<view>/<slice>": "<sha1 hex>", ...}
    }

- The flat ``fragments`` map keys sort lexically and diff trivially;
  EMPTY fragments are omitted, so "fragment never created" and
  "fragment cleared to zero bits" — which serve identical answers —
  digest identically (anti-entropy repair relies on this: clearing a
  divergent extra fragment converges the digests).
- The top-level ``digest`` makes the common all-equal sweep one string
  compare; the map is only walked when it differs.
- Determinism: iteration is sorted at every level and
  ``Fragment.checksum()`` is a pure function of the logical bit set
  (position-bound block hashes, write-order independent — the property
  tests/test_fragment_stateful.py pins), so the digest is a pure
  function of (schema, bits).
"""

from __future__ import annotations

import hashlib
import json
from typing import NamedTuple, Optional

#: Checksum of a fragment with no bits (sha1 over zero blocks) — such
#: fragments are omitted from the digest (see module docstring).
EMPTY_FRAGMENT_CHECKSUM = hashlib.sha1().digest()


def fragment_path(index: str, frame: str, view: str, slice_i: int) -> str:
    """Digest-map key for one fragment (names never contain ``/``)."""
    return f"{index}/{frame}/{view}/{slice_i}"


def parse_fragment_path(path: str) -> tuple[str, str, str, int]:
    index, frame, view, slice_s = path.split("/")
    return index, frame, view, int(slice_s)


def fragment_query(path: str) -> str:
    """The ``?index=..&frame=..&view=..&slice=..`` query string for the
    fragment-data / import-roaring endpoints."""
    index, frame, view, slice_i = parse_fragment_path(path)
    return f"index={index}&frame={frame}&view={view}&slice={slice_i}"


def holder_digest(holder) -> dict:
    """Compute one group's digest over its live holder (see module
    docstring for the shape).  Sorted at every level; empty fragments
    omitted."""
    fragments: dict[str, str] = {}
    for idx_name, idx in sorted(holder.indexes.items()):
        for f_name, frame in sorted(idx.frames.items()):
            for v_name, view in sorted(frame.views.items()):
                for slice_i, frag in sorted(view.fragments.items()):
                    chk = frag.checksum()
                    if chk == EMPTY_FRAGMENT_CHECKSUM:
                        continue
                    fragments[fragment_path(idx_name, f_name, v_name, slice_i)] = (
                        chk.hex()
                    )
    schema = holder.schema()
    h = hashlib.sha1()
    h.update(json.dumps(schema, sort_keys=True, separators=(",", ":")).encode())
    for path in sorted(fragments):
        h.update(path.encode())
        h.update(fragments[path].encode())
    return {"digest": h.hexdigest(), "schema": schema, "fragments": fragments}


class DigestDiff(NamedTuple):
    """Donor-vs-laggard fragment plan (resync direction: make the
    laggard's bytes the donor's)."""

    #: Fragment paths to stream donor -> laggard: present on the donor
    #: but missing or differing on the laggard, plus laggard extras
    #: whose (index, frame) still exists on the donor (the donor's 404
    #: streams as a clear).
    stream: list[str]
    #: Index names the laggard holds that the donor does not (delete).
    drop_indexes: list[str]
    #: (index, frame) pairs the laggard holds inside donor indexes that
    #: the donor does not (delete).
    drop_frames: list[tuple[str, str]]


def _schema_tree(schema: list) -> dict[str, set[str]]:
    return {
        i.get("name", ""): {f.get("name", "") for f in i.get("frames", [])}
        for i in (schema or [])
    }


def diff_digests(donor: dict, laggard: dict) -> DigestDiff:
    """The resync plan that converges ``laggard`` onto ``donor``."""
    d_frags = donor.get("fragments") or {}
    l_frags = laggard.get("fragments") or {}
    d_tree = _schema_tree(donor.get("schema"))
    l_tree = _schema_tree(laggard.get("schema"))
    stream = [p for p in sorted(d_frags) if l_frags.get(p) != d_frags[p]]
    drop_indexes = sorted(set(l_tree) - set(d_tree))
    drop_frames = sorted(
        (i, f)
        for i, frames in l_tree.items()
        if i in d_tree
        for f in frames - d_tree[i]
    )
    # Laggard extras inside surviving (index, frame) pairs: the donor
    # answers 404 for them and the stream path clears them.
    dropped = set(drop_indexes)
    dropped_frames = set(drop_frames)
    for p in sorted(set(l_frags) - set(d_frags)):
        index, frame, _view, _s = parse_fragment_path(p)
        if index in dropped or (index, frame) in dropped_frames:
            continue
        stream.append(p)
    return DigestDiff(stream, drop_indexes, drop_frames)


class RepairPlan(NamedTuple):
    """Anti-entropy repair plan across N healthy groups."""

    #: group name -> sorted fragment paths to repair on it.
    divergent: dict[str, list[str]]
    #: fragment path -> donor group name holding the winning copy.
    donor: dict[str, str]
    #: First differing path (lexically) — the structured divergence
    #: log's pointer at WHERE the groups disagree.
    first_path: Optional[str]


def majority_plan(digests: dict[str, dict]) -> RepairPlan:
    """Compare the healthy groups' digests; for every divergent
    fragment path the MAJORITY copy wins (ties break to the copy held
    by the lexically smallest group name, so every router instance
    derives the same plan) and minority holders are scheduled for
    repair.  A majority that LACKS the fragment wins too: the plan
    streams a clear (the donor's 404) to the holders."""
    names = sorted(digests)
    all_paths = sorted({p for d in digests.values() for p in (d.get("fragments") or {})})
    divergent: dict[str, list[str]] = {}
    donor: dict[str, str] = {}
    first_path: Optional[str] = None
    for path in all_paths:
        held = {n: (digests[n].get("fragments") or {}).get(path) for n in names}
        values = set(held.values())
        if len(values) == 1:
            continue
        if first_path is None:
            first_path = path
        counts: dict[Optional[str], int] = {}
        for v in held.values():
            counts[v] = counts.get(v, 0) + 1
        # Majority copy; ties -> the copy held by the smallest group
        # name (deterministic across routers and runs).
        winner = min(
            counts,
            key=lambda v: (
                -counts[v],
                min(n for n in names if held[n] == v),
            ),
        )
        donor_name = min(n for n in names if held[n] == winner)
        for n in names:
            if held[n] != winner:
                divergent.setdefault(n, []).append(path)
                donor.setdefault(path, donor_name)
    return RepairPlan(divergent, donor, first_path)
