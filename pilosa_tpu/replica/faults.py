"""Deterministic fault injection for the replica serving tier.

Partial-failure orderings — shed-after-commit, crash mid-fan-out, crash
mid-replay — are the whole correctness surface of the durable write
path, and they are exactly the orderings that only ever show up in
production.  This seam makes them REPRODUCIBLE: a seeded spec string
(``PILOSA_TPU_FAULT_SPEC``) arms faults at the two crossings every
write takes — the router's per-group HTTP forward and the WAL append —
so a tier-1 test (or an operator's game-day) can replay the same
interleaving every run.

Spec grammar (``;``-separated rules)::

    spec   := rule (';' rule)*
    rule   := 'seed=' INT
            | site ['/' key] ':' action ['@' nth] ['~' prob]
    site   := 'forward' | 'wal.append' | 'catchup'
            | 'resync.digest' | 'resync.fetch' | 'resync.chunk'
            | 'resync.seed'
    action := 'drop' | 'crash' | 'delay=' MS | 'error=' STATUS

- ``site`` is the crossing: ``forward`` fires inside the router's
  per-group HTTP exchange (reads, write fan-out, catch-up replays, AND
  resync streams all cross it), ``wal.append`` inside the log append
  (before the record is durable), ``catchup`` at the top of each
  replay round.  The ``resync.*`` sites cover the automated-resync
  round (replica/resync.py): ``resync.digest`` before each digest
  fetch (key = the group asked), ``resync.fetch`` before each donor
  fragment fetch (key = donor), ``resync.chunk`` before each chunk
  push — including the resume probe — (key = laggard), and
  ``resync.seed`` inside the sequencer-locked seed-seq exchange (key =
  laggard), so torn-transfer, donor-death-mid-stream, and
  crash-before-seed orderings replay deterministically.
- ``key`` scopes a rule to one group name (``forward/g2:...``); no key
  matches every hit of the site.
- ``@nth`` fires on exactly the nth matching hit (1-based) — the
  deterministic ordering knob: ``forward/g2:drop@3`` kills the third
  crossing to g2 and nothing else.
- ``~prob`` fires each hit with probability ``prob`` drawn from the
  spec-level seeded RNG (``seed=42;forward:drop~0.01``) — same seed,
  same spec, same decisions, run after run.
- actions: ``drop`` raises a transport error (the router's failover /
  demotion trigger), ``error=503`` synthesizes an HTTP answer with that
  status, ``delay=250`` sleeps that many ms then proceeds, ``crash``
  exits the process hard (``os._exit``) — the subprocess crash tests'
  kill switch, firing BEFORE the guarded operation completes.

A rule with neither ``@nth`` nor ``~prob`` fires on every matching hit.
"""

from __future__ import annotations

import os
import random
import threading

from pilosa_tpu.analysis import lockcheck
import time
from typing import Optional

SPEC_ENV = "PILOSA_TPU_FAULT_SPEC"

# Exit code for the 'crash' action: distinctive, so a harness can tell
# an injected crash from a real one.
CRASH_EXIT_CODE = 86


class FaultError(OSError):
    """An injected transport failure (the ``drop`` action).  Subclasses
    OSError so every caller's existing connect-failure handling —
    failover, demotion, catch-up abort — engages unchanged."""


class InjectedStatus(Exception):
    """An injected HTTP answer (the ``error=<status>`` action): the
    crossing synthesizes a response with this status instead of talking
    to the group."""

    def __init__(self, status: int):
        super().__init__(f"injected HTTP {status}")
        self.status = status


class _Rule:
    __slots__ = ("site", "key", "action", "arg", "nth", "prob", "hits")

    def __init__(self, site: str, key: str, action: str, arg: float,
                 nth: Optional[int], prob: Optional[float]):
        self.site = site
        self.key = key
        self.action = action
        self.arg = arg
        self.nth = nth
        self.prob = prob
        self.hits = 0

    def __repr__(self) -> str:  # debugging / stats strings
        where = f"{self.site}/{self.key}" if self.key else self.site
        when = f"@{self.nth}" if self.nth else (f"~{self.prob}" if self.prob else "")
        return f"{where}:{self.action}{when}"


def _parse_rule(raw: str) -> _Rule:
    head, _, action = raw.partition(":")
    if not action:
        raise ValueError(f"fault rule {raw!r}: missing ':action'")
    site, _, key = head.partition("/")
    nth: Optional[int] = None
    prob: Optional[float] = None
    if "~" in action:
        action, _, p = action.partition("~")
        prob = float(p)
    if "@" in action:
        action, _, n = action.partition("@")
        nth = int(n)
    action, _, arg_s = action.partition("=")
    action = action.strip()
    if action not in ("drop", "crash", "delay", "error"):
        raise ValueError(f"fault rule {raw!r}: unknown action {action!r}")
    arg = float(arg_s) if arg_s else 0.0
    return _Rule(site.strip(), key.strip(), action, arg, nth, prob)


class FaultInjector:
    """Armed fault rules; thread-safe, deterministic per (spec, seed)."""

    def __init__(self, rules: list[_Rule], seed: int = 0):
        self.rules = rules
        self._rng = random.Random(seed)
        self._mu = lockcheck.named_lock("replica.faults._mu")

    @classmethod
    def from_spec(cls, spec: str) -> "FaultInjector":
        seed = 0
        rules = []
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("seed="):
                seed = int(raw[5:])
                continue
            rules.append(_parse_rule(raw))
        return cls(rules, seed=seed)

    @classmethod
    def from_env(cls, env=None) -> Optional["FaultInjector"]:
        spec = (env if env is not None else os.environ).get(SPEC_ENV, "").strip()
        return cls.from_spec(spec) if spec else None

    def hit(self, site: str, key: str = "") -> None:
        """One crossing of ``site`` (optionally scoped by ``key``).
        Raises :class:`FaultError` / :class:`InjectedStatus`, sleeps, or
        exits the process when an armed rule fires; otherwise no-op."""
        fired: Optional[_Rule] = None
        with self._mu:
            for r in self.rules:
                if r.site != site or (r.key and r.key != key):
                    continue
                r.hits += 1
                if r.nth is not None:
                    if r.hits != r.nth:
                        continue
                elif r.prob is not None:
                    if self._rng.random() >= r.prob:
                        continue
                fired = r
                break
        if fired is None:
            return
        if fired.action == "delay":
            time.sleep(fired.arg / 1000.0)
            return
        if fired.action == "drop":
            raise FaultError(f"injected fault: {fired!r}")
        if fired.action == "error":
            raise InjectedStatus(int(fired.arg or 503))
        # crash: exit hard, mid-operation — the durable state on disk is
        # whatever the guarded code managed before this line.
        os._exit(CRASH_EXIT_CODE)


#: Shared no-op: lets call sites write ``self.faults.hit(...)``
#: unconditionally.
class _NopInjector:
    rules: list = []

    def hit(self, site: str, key: str = "") -> None:
        return


NOP_FAULTS = _NopInjector()
