"""The replica read router: one front door over N serving groups.

The reference fans a read to ANY of a fragment's ``ReplicaN`` owners at
query time (executor.go:1147-1159) — replication buys read throughput,
not just durability.  This router is that idea at GROUP granularity:
each group is a complete serving unit (a lockstep job or a plain
server) holding a full copy of every slice, so ANY group can answer ANY
read and read QPS scales with group count.

Routing policy:

- CLASSIFY with the QoS classifier (``qos.classify_request`` — the same
  byte-scan the admission door uses, so a request is a write here iff
  it is a write there).  A false read->write positive only costs fan-out
  latency; a false negative is impossible for PQL mutating calls.
- READS (and admin GETs) go to ONE healthy, CAUGHT-UP group:
  least-inflight pick, ties broken by fewest-routed so an idle router
  round-robins.  On a connect failure or a 5xx answer the group is
  marked unhealthy and the read fails over ONCE to a sibling group
  (reads are side-effect-free, so the retry is safe; ``[replica]
  failover = false`` disables it).  A lagging group never serves reads
  — that is what preserves read-your-writes across groups now that a
  write can commit without it.
- WRITES (and mutating admin — schema must stay identical everywhere)
  run through ONE sequencer: each accepted write is assigned a
  monotonic sequence number and appended to the WRITE-AHEAD LOG
  (``replica/wal.py``) BEFORE any group sees it, then fanned to every
  in-rotation group with the sequence riding ``X-Pilosa-Write-Seq``.
  The sequencer lock is held for the whole fan-out, so every group
  applies every write in the same total order and the groups' fragment
  generation vectors advance identically — the invariant that keeps
  each group's qcache and serve-state repair read-your-writes correct
  with zero cross-group invalidation traffic.

Failure semantics (the durable-log upgrade of PR 6's full-set rule):

- QUORUM is now a MAJORITY of the configured groups.  A write COMMITS
  (2xx to the client) once >= majority of groups applied it; groups
  that are down, lagging, or failed mid-fan-out simply miss the write
  and accumulate a bounded backlog in the WAL instead of blocking the
  cluster — one dead group no longer 503s every write.  Writes refuse
  (503 + Retry-After, touching no group and appending nothing) only
  when fewer than a majority of groups are in rotation.
- A write that reached SOME group but fewer than a majority answers
  502 "may be partially applied": the record stays in the log, the
  laggards re-converge by replay, and the idempotent client retry is
  harmless.
- A write SHED by a group (429, or any answer carrying Retry-After —
  the admission door under load; one shared predicate,
  ``replica.write_not_applied``, decides "did not land" for the
  fan-out, the catch-up replay, and the group-side bookkeeping alike)
  is load-dependent, not deterministic: shed before ANY group
  committed — and with no AMBIGUOUS failure earlier in the fan-out —
  passes the backpressure through verbatim and ABORTS the log record
  (tombstoned — replay can never deliver a write no live group holds);
  shed after a sibling committed just makes the shedding group a
  laggard (demoted + replayed later), and the write still commits if a
  majority applied.
- A transport failure (or 5xx) is AMBIGUOUS: the socket may have died
  AFTER the group applied the write, so it never proves
  non-application.  Only provable refusals (shed / deterministic 4xx
  everywhere) tombstone the record; when every group failed
  ambiguously the record STAYS LIVE (502 "may be partially applied" to
  the client) and catch-up re-delivers it — idempotent re-apply is the
  contract, silent cross-group divergence is not.
- A read answered 504 spent ITS OWN deadline budget — request-scoped,
  not a group-health signal — so it returns to the client without
  demoting the group.
- RECOVERY is probe + replay: a background loop probes down/lagging
  groups with jittered exponential backoff per group (``[replica]
  probe-interval`` base, doubled per failed probe up to
  ``probe-max-interval``, reset on recovery — a dead group is not
  hammered in lockstep by every router).  A live group reporting a
  stale applied sequence gets the missed WAL suffix streamed in order
  (``replica/catchup.py``; epoch-guarded, so a restarted incarnation
  can't absorb a replay paced against its predecessor) and only
  rejoins the read rotation once FULLY caught up.  A laggard whose
  backlog would grow the WAL past ``wal-max-bytes`` is declared STALE
  (``replica.stale.<g>``): the log compacts past it, and the probe —
  which keeps visiting stale groups at ``probe-max-interval`` — drives
  an AUTOMATED RESYNC (``replica/resync.py``): digest diff against a
  healthy donor, differing fragments streamed as serialized roaring
  payloads, applied-sequence seeded under the sequencer lock, WAL
  catch-up for the final drain — no human in the loop.  A group
  reporting ``applied_seq=0`` over a non-empty sequence space (blank
  data dir) takes the same path.
- ANTI-ENTROPY: an optional background sweep (``[replica]
  anti-entropy-interval``, jittered, off by default) compares healthy
  groups' content digests under the sequencer lock and repairs any
  silently diverged fragment from the majority copy
  (``replica.divergence.<g>`` + one structured
  ``pilosa_tpu.divergence`` log line per divergent sweep).

Observability: ``replica.routed.<group>`` / ``replica.failover`` /
``replica.write_fanout`` (+ refused/error/shed), per-group
``replica.healthy.<group>`` / ``replica.inflight.<group>`` /
``replica.lag.<group>`` gauges and ``replica.wal_bytes`` at the
router's own ``/debug/vars``; ``/replica/status`` returns the live
group table (health, applied sequence, lag, caught-up/stale flags) and
the WAL head/tail.  Routed requests tag their trace root with
``group=<g>`` and graft the group's span tree under the forward span.
Deterministic fault injection (``replica/faults.py``,
``PILOSA_TPU_FAULT_SPEC``) hooks the per-group forward and the WAL
append, so partial-failure orderings are reproducible in tests.

PARTITIONED REPLICA GROUPS (PR 17): the router can run a 2-D
(slice-shard x replica) layout — a :class:`~pilosa_tpu.replica.shards.ShardMap`
partitions the slice space into contiguous ranges, each shard owning
its own replica set and its OWN sequence space (:class:`ShardRuntime`:
per-shard WAL, per-shard sequencer lock, per-shard catch-up / resync /
compaction — the PR 7/9 machinery runs per shard UNCHANGED because
applied-seq marks and digests are keyed inside one shard's group set).
Reads compute the query's slice cover and fan out only to the shards
touched, merging results exactly like the executor's cluster fan-out;
PQL writes route to the one shard owning ``columnID``'s slice, so two
shards sequence writes CONCURRENTLY — write throughput scales with the
shard axis, which one global sequencer lock never allowed.  Live
resharding (``POST /replica/reshard``) splits a shard with zero
downtime: fragments pre-stream to the new owners while the old shard
keeps serving, then an EPOCH FENCE briefly holds new requests at the
routing gate, streams the delta, flips the map, clears the moved
range off the old owners, and compacts the old WAL — writes in the
moved range block for the fence and then land on the new shard; none
fail.  The default single-shard map is byte-for-byte the pre-shard
router: same lock, same WAL path, same status payloads.
"""

from __future__ import annotations

import json
import logging
import os
import random
import re
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlencode, urlparse

from pilosa_tpu import costs as costs_mod
from pilosa_tpu import metrics as metrics_mod
from pilosa_tpu import pql
from pilosa_tpu import qos
from pilosa_tpu.analysis import lockcheck
from pilosa_tpu.analysis import spec
from pilosa_tpu.pilosa import SLICE_WIDTH
from pilosa_tpu.pql.ast import WRITE_CALL_NAMES
from pilosa_tpu.qos import DEADLINE_HEADER
from pilosa_tpu.replica import (
    APPLIED_SEQ_HEADER,
    GROUP_HEADER,
    REPLAY_HEADER,
    WRITE_SEQ_HEADER,
    write_not_applied,
)
from pilosa_tpu.replica.catchup import CatchupManager
from pilosa_tpu.replica.digest import (
    fragment_query,
    majority_plan,
    parse_fragment_path,
)
from pilosa_tpu.replica.faults import FaultInjector, InjectedStatus, NOP_FAULTS
from pilosa_tpu.replica.resync import ResyncAbort, ResyncManager
from pilosa_tpu.replica.shards import (
    Shard,
    ShardMap,
    ShardMapError,
    parse_shard_map,
    single_shard_map,
    uniform_shard_map,
)
from pilosa_tpu.replica.wal import WriteAheadLog
from pilosa_tpu.stats import NOP_STATS
from pilosa_tpu.trace import TRACE_HEADER, TRACE_SPANS_HEADER

# Structured divergence log: one line per anti-entropy sweep that found
# healthy groups disagreeing (the slowquery-logger pattern) — counted
# AND logged because divergence is a correctness event, not load noise.
_divergence_logger = logging.getLogger("pilosa_tpu.divergence")

# Headers never forwarded on a hop: ownership is per-connection, the
# router recomputes lengths, deadline/trace headers are REWRITTEN
# (remaining budget, router trace id), and the write-sequence/replay
# headers are ROUTER-OWNED (a client must not be able to spoof a
# group's applied mark).
_HOP_HEADERS = frozenset(
    ("host", "content-length", "connection", "accept-encoding",
     DEADLINE_HEADER.lower(), TRACE_HEADER.lower(),
     WRITE_SEQ_HEADER.lower(), REPLAY_HEADER.lower())
)


@lockcheck.guarded_class
class GroupState:
    """Router-side record of one serving group."""

    __slots__ = ("name", "base", "healthy", "inflight", "routed", "epoch",
                 "applied_seq", "caught_up", "stale", "suspect",
                 "probe_delay", "probe_at", "__weakref__")

    # Lockset race detector declarations: the group table is written by
    # HTTP handler threads (reads, writes), the probe thread, and the
    # catch-up/resync/anti-entropy paths concurrently — every post-init
    # write must hold the router's table lock.  (The sequencer lock
    # alone is NOT enough: reads route off this state without it.)
    _guarded_by_ = {
        "healthy": "replica.router._mu",
        "inflight": "replica.router._mu",
        "routed": "replica.router._mu",
        "epoch": "replica.router._mu",
        "applied_seq": "replica.router._mu",
        "caught_up": "replica.router._mu",
        "stale": "replica.router._mu",
        "suspect": "replica.router._mu",
        "probe_delay": "replica.router._mu",
        "probe_at": "replica.router._mu",
    }

    def __init__(self, name: str, base: str):
        self.name = name
        if "://" not in base:
            base = "http://" + base
        self.base = base.rstrip("/")
        self.healthy = True
        self.inflight = 0
        self.routed = 0
        self.epoch: Optional[str] = None  # last X-Pilosa-Group seen
        # Durable-write bookkeeping: the highest WAL sequence this group
        # is known to have applied (advanced on write acks, read
        # passively off X-Pilosa-Applied-Seq, authoritative from the
        # health probe), whether it is fully caught up to the WAL head
        # (only caught-up groups serve reads or receive new writes),
        # and whether it fell so far behind the WAL compacted past it
        # (stale: operator resync required).
        self.applied_seq = 0
        self.caught_up = True
        self.stale = False
        # Content-suspect: the group answered a write with a 4xx a
        # sibling 2xx'd — for IDENTICAL replicated state that is
        # impossible, so its content is presumed diverged (blank data
        # dir, lost index) until a digest check against a healthy donor
        # clears it (or a resync round repairs it).
        self.suspect = False
        # Probe backoff (jittered exponential, per group).
        self.probe_delay = 0.0
        self.probe_at = 0.0

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "base": self.base,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "routed": self.routed,
            "epoch": self.epoch,
            "appliedSeq": self.applied_seq,
            "caughtUp": self.caught_up,
            "stale": self.stale,
            "suspect": self.suspect,
        }


def _parse_group_spec(i: int, spec: str) -> GroupState:
    """``host:port`` or ``name=host:port`` (names default to g<i>)."""
    spec = spec.strip()
    if "=" in spec and "://" not in spec.split("=", 1)[0]:
        name, base = spec.split("=", 1)
        return GroupState(name.strip(), base.strip())
    return GroupState(f"g{i}", spec)


_QUERY_PATH_RE = re.compile(r"^/index/([^/]+)/query$")


def _merge_result_values(vals: list):
    """Merge one PQL call's per-shard results, mirroring the executor's
    cluster reduce: bools OR (mutations), counts SUM, bitmaps UNION
    bits + merged attrs, TopN pair lists SUM counts by id (descending
    count, id tiebreak — the executor's ordering)."""
    vals = [v for v in vals if v is not None]
    if not vals:
        return None
    v0 = vals[0]
    if isinstance(v0, bool):
        return any(vals)
    if isinstance(v0, (int, float)):
        return sum(vals)
    if isinstance(v0, dict) and "bits" in v0:
        bits: set = set()
        attrs: dict = {}
        for v in vals:
            bits.update(v.get("bits") or [])
            attrs.update(v.get("attrs") or {})
        return {"attrs": attrs, "bits": sorted(bits)}
    if isinstance(v0, list):
        counts: dict = {}
        for v in vals:
            for pair in v:
                if isinstance(pair, dict) and "id" in pair:
                    counts[pair["id"]] = (
                        counts.get(pair["id"], 0) + pair.get("count", 0)
                    )
        return [
            {"id": i, "count": c}
            for i, c in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        ]
    return v0


def _merge_query_payloads(payloads: list) -> bytes:
    """Merge per-shard ``/index/<i>/query`` JSON bodies into one
    response: results merged element-wise, columnAttrSets concatenated
    and deduplicated by id."""
    docs = []
    for p in payloads:
        try:
            docs.append(json.loads(p or b"{}"))
        except ValueError:
            docs.append({})
    n = max((len(d.get("results") or []) for d in docs), default=0)
    results = [
        _merge_result_values([
            (d.get("results") or [None] * n)[i] if i < len(d.get("results") or []) else None
            for d in docs
        ])
        for i in range(n)
    ]
    out: dict = {"results": results}
    attr_sets: list = []
    seen_ids: set = set()
    for d in docs:
        for cs in d.get("columnAttrSets") or []:
            key = cs.get("id") if isinstance(cs, dict) else None
            if key is not None and key in seen_ids:
                continue
            if key is not None:
                seen_ids.add(key)
            attr_sets.append(cs)
    if attr_sets:
        out["columnAttrSets"] = attr_sets
    return json.dumps(out).encode()


@lockcheck.guarded_class
class ShardRuntime:
    """One shard's serving state: a contiguous slice range, its replica
    set, and its OWN sequence space — WAL, sequencer lock, write
    high-water mark, catch-up, resync, compaction floors.

    This object IS the seam that lets the PR 7/9 recovery machinery run
    per shard unchanged: :class:`CatchupManager` and
    :class:`ResyncManager` take it where they used to take the router,
    and it exposes the same attributes (``_forward`` / ``_mu`` /
    ``faults`` / ``_seq_mu`` / ``_resync_floor`` / ``catchup`` /
    ``wal``) scoped to this shard's groups and log.

    Every shard's sequencer lock carries the same lockcheck NAME
    (``replica.router._seq_mu``): the name identifies the lock's
    CONTRACT — the blocking allowlist pairs it with socket/fsync
    because holding the order lock across the fan-out IS the design —
    while each shard holds its own instance, so two shards sequence
    writes concurrently.  Shard sequencer locks never nest."""

    # Per-shard write-sequence high-water mark: part of the total order
    # THIS shard's sequencer lock defines.
    _guarded_by_ = {"write_seq": "replica.router._seq_mu"}

    def __init__(self, router: "ReplicaRouter", shard: Shard,
                 groups: list, wal: WriteAheadLog):
        self.router = router
        self.name = shard.name
        self.lo = shard.lo
        self.hi = shard.hi  # exclusive; None = open-ended
        self.group_specs = list(shard.group_specs)
        self.groups = groups
        self.wal = wal
        self.stats = router.stats
        self.faults = router.faults
        # The shared group-table lock (one per router — GroupState's
        # _guarded_by_ names it) and the per-shard sequencer instance.
        self._mu = router._mu
        self._seq_mu = lockcheck.named_lock("replica.router._seq_mu")
        self.write_seq = wal.last_seq
        # Per-group compaction floors for in-flight resync rounds on
        # THIS shard (guarded by the shared table lock).
        self._resync_floor: dict[str, int] = {}
        self.catchup = CatchupManager(self, wal, stats=router.stats,
                                      budgets=router.budgets)
        self.resync = ResyncManager(
            self, wal, stats=router.stats,
            chunk_bytes=router.resync_chunk_bytes,
            columnar=router.resync_columnar,
            budgets=router.budgets,
        )
        # A (re)start over a non-empty log: no group may be assumed
        # current (see ReplicaRouter.__init__).
        if wal.last_seq > 0:
            for g in groups:
                g.caught_up = False
        spec.emit("config", src=id(wal), shard=self.name,
                  groups=[g.name for g in groups], quorum=self.quorum)

    def owns(self, slice_i: int) -> bool:
        return slice_i >= self.lo and (self.hi is None or slice_i < self.hi)

    @property
    def _forward(self):
        """Live dereference of the router's forwarder — NOT captured at
        init, so a monkeypatched/fault-wrapped ``router._forward`` is
        seen by every shard and by catch-up/resync through the facade."""
        return self.router._forward

    @property
    def quorum(self) -> int:
        """Writes commit on a MAJORITY of THIS shard's group set."""
        return len(self.groups) // 2 + 1

    def _ready_groups(self) -> list:
        """This shard's write rotation: reachable, fully caught up to
        the shard's WAL head, and not stale."""
        with self._mu:
            return [
                g for g in self.groups
                if g.healthy and g.caught_up and not g.stale
            ]

    def quorate(self) -> bool:
        return len(self._ready_groups()) >= self.quorum

    def _pick(self, exclude=None) -> Optional[GroupState]:
        """Least-inflight healthy CAUGHT-UP group of this shard (ties:
        fewest routed).  A lagging group is invisible to reads until
        catch-up finishes — the read-your-writes rule, per shard."""
        with self._mu:
            live = [
                g for g in self.groups
                if g.healthy and g.caught_up and not g.stale
                and (exclude is None or g is not exclude)
            ]
            if not live:
                return None
            g = min(live, key=lambda g: (g.inflight, g.routed))
            g.routed += 1
            g.inflight += 1
            self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)
            # Emitted under _mu so the (group, applied) observation is
            # consistent with the pick itself.
            spec.emit("read", src=id(self.wal), group=g.name,
                      applied=g.applied_seq)
        self.stats.count(f"replica.routed.{g.name}")
        return g

    def _mark_lagging(self, g: GroupState) -> None:
        """The group missed a sequenced write on this shard: out of the
        read rotation until catch-up replays it to the shard's head."""
        with self._mu:
            g.caught_up = False
        self.stats.gauge(
            f"replica.lag.{g.name}", max(0, self.wal.last_seq - g.applied_seq)
        )

    # -- the per-shard write sequencer ------------------------------------

    def sequence_write(self, method: str, path_qs: str, body: bytes,
                       headers: dict, deadline=None, trace=None):
        """Sequence one write into THIS shard's WAL, then total-ordered
        fan-out over this shard's groups.  The shard's sequencer lock is
        held end to end, so every group of the shard applies every one
        of its writes in one total order — while sibling shards
        sequence their own writes concurrently under their own locks.
        COMMIT RULE (unchanged from the single-sequencer router):
        >= majority applied -> 2xx; some but fewer -> 502 (record
        stays, laggards replay); PROVABLY none (shed / deterministic
        4xx everywhere, no ambiguous failure) -> the record is aborted
        and the refusal surfaces verbatim; applied nowhere but
        AMBIGUOUSLY -> the record stays live and replays, 502."""
        router = self.router
        with self._seq_mu:
            ready = self._ready_groups()
            if len(ready) < self.quorum:
                with self._mu:
                    out_names = [
                        g.name for g in self.groups
                        if not (g.healthy and g.caught_up and not g.stale)
                    ]
                self.stats.count("replica.write_refused")
                if trace is not None:
                    trace.root.tags["qos"] = "write_refused"
                return router._shed(
                    503,
                    f"write refused: shard {self.name} group set not quorate "
                    f"(need {self.quorum}/{len(self.groups)}, out: {', '.join(out_names)})",
                    retry_after=1.0,
                )
            # DURABILITY FIRST: the record is in the log (fsync-batched)
            # before any group sees the write — a router crash mid-fan-out
            # replays the tail instead of losing the order.
            try:
                seq = self.wal.append(
                    method, path_qs, body, headers.get("content-type", "")
                )
            except OSError as e:
                self.stats.count("replica.wal_error")
                return router._shed(
                    503, f"write log append failed: {e}", retry_after=1.0
                )
            self.write_seq = seq
            self.stats.count(f"replica.shard.writes.{self.name}")
            # Groups outside the rotation miss this sequence: their
            # backlog grows in the WAL until catch-up (or staleness).
            for g in self.groups:
                if g not in ready:
                    self._mark_lagging(g)
            first_out = None  # first answer of any kind
            first_ok = None  # first 2xx — the committed write's answer
            deterministic_4xx = None
            det4xx_groups: list = []  # groups that answered it
            applied = 0
            # Ambiguous failure: a transport error (or 5xx) proves
            # NOTHING about application — the group may have applied
            # the write before the socket died — so once one happens
            # the record can never be tombstoned this round.
            ambiguous = False
            for g in ready:
                sp = trace.root.child("forward") if trace is not None else None
                with self._mu:  # inflight is shared with _pick/_release
                    g.inflight += 1
                    self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)
                try:
                    out = self._forward(
                        g, method, path_qs, body, headers, deadline=deadline,
                        trace_id=(trace.id if trace is not None else ""),
                        extra_headers={WRITE_SEQ_HEADER: str(seq)},
                    )
                except OSError as e:
                    if sp is not None:
                        sp.finish().annotate(group=g.name, error=str(e))
                    router._mark_unhealthy(g, str(e))
                    self._mark_lagging(g)
                    self.stats.count("replica.write_error")
                    ambiguous = True
                    continue
                finally:
                    router._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, status=out[0])
                # ONE predicate ("did the write land?") shared with the
                # catch-up replay and the group-side bookkeeping: a
                # shed (429, or any answer carrying Retry-After) is
                # LOAD-dependent, not deterministic — under load one
                # group can shed a write its siblings applied, so it
                # must never be ACKed as a success.
                missed = write_not_applied(out[0], out[3].get("Retry-After"))
                shed = missed and out[0] < 500
                if shed and applied == 0 and not ambiguous:
                    # Shed before ANY group committed, with no
                    # ambiguous failure earlier in the fan-out: nothing
                    # is applied anywhere, so abort the log record
                    # (replay must never deliver it) and pass the
                    # backpressure through verbatim — no demotion (the
                    # group is loaded, not broken); the client retries.
                    self.wal.abort(seq)
                    self.stats.count("replica.write_shed")
                    spec.emit("ack", src=id(self.wal), seq=seq,
                              status=out[0], applied=0)
                    extra = {GROUP_HEADER: g.name}
                    ra = out[3].get("Retry-After")
                    if ra:
                        extra["Retry-After"] = ra
                    return out[0], out[1], out[2], extra
                if missed:
                    # Failed (or shed) after a sibling committed or an
                    # ambiguous failure: this group missed sequence
                    # ``seq``.  Demote it — the probe + catch-up
                    # replays the suffix and only then re-admits it —
                    # and keep fanning: with the WAL holding the
                    # record, one group's failure no longer aborts the
                    # commit.
                    router._mark_unhealthy(g, f"HTTP {out[0]} on write")
                    self._mark_lagging(g)
                    self.stats.count("replica.write_error")
                    if out[0] >= 500:
                        ambiguous = True
                    continue
                with self._mu:
                    g.applied_seq = max(g.applied_seq, seq)
                spec.emit("apply", src=id(self.wal), group=g.name, seq=seq,
                          ok=out[0] < 300)
                if out[0] < 300:
                    applied += 1
                    if first_ok is None:
                        first_ok = out
                else:
                    # Deterministic 4xx (parse/schema: 400/404/409)
                    # answers identically on every group (identical
                    # schema + total order) — keep fanning so a
                    # mutating call that DID apply elsewhere stays
                    # aligned; the group's applied mark still advances
                    # (replaying it would just re-answer the same 4xx).
                    # If a SIBLING 2xx'd this very write the premise is
                    # broken — see the suspect check below the loop.
                    if deterministic_4xx is None:
                        deterministic_4xx = out
                    det4xx_groups.append(g)
                if first_out is None:
                    first_out = out
            if applied > 0 and det4xx_groups:
                # A 4xx is only "deterministic" while every replica
                # answers it.  One group 4xx-ing a write a sibling
                # APPLIED means its content diverged (a blank data dir
                # 404s the index every sibling holds; a half-applied
                # create 409s) — silently counting it applied is
                # exactly the latent divergence this tier exists to
                # kill.  Mark it SUSPECT and pull it from rotation: the
                # probe digest-checks it against a healthy donor and
                # either clears the flag (retried creates legitimately
                # answer 409 on the groups that already applied them)
                # or drives a resync round that repairs it.
                for sg in det4xx_groups:
                    with self._mu:
                        sg.suspect = True
                        sg.caught_up = False
                    self.stats.count(f"replica.suspect.{sg.name}")
                    router._mark_unhealthy(
                        sg, f"divergent answer on write {seq}"
                    )
            if applied >= self.quorum:
                # COMMITTED: a majority holds the write; any laggard
                # re-converges from the log.
                self.stats.count("replica.write_fanout")
                status, ctype, payload, _rh = first_ok or first_out
                spec.emit("ack", src=id(self.wal), seq=seq, status=status,
                          applied=applied)
                result = (status, ctype, payload, {GROUP_HEADER: "all"})
            elif applied == 0 and deterministic_4xx is not None and not ambiguous:
                # Every in-rotation group answered the same
                # deterministic 4xx: PROVABLY applied nowhere, nothing
                # to replay — tombstone the record and surface the
                # answer.
                self.wal.abort(seq)
                status, ctype, payload, _rh = deterministic_4xx
                spec.emit("ack", src=id(self.wal), seq=seq, status=status,
                          applied=0)
                result = (status, ctype, payload, {GROUP_HEADER: "all"})
            else:
                # Reached some group but not a majority — or applied
                # nowhere WE CAN PROVE (every group transport-failed /
                # 5xx'd, or shed after one did; a socket that died
                # after the request was sent may still have delivered
                # the write).  Tombstoning here could hide a write one
                # group actually holds — replay would then never
                # deliver it to the siblings, permanent cross-group
                # divergence — so the record STAYS LIVE: every demoted
                # group gets it re-delivered by catch-up (idempotent
                # re-apply is the contract) and the client hears 502
                # "may be partially applied" (retry is harmless).
                failed_names = ", ".join(
                    g.name for g in ready if g.applied_seq < seq
                )
                spec.emit("ack", src=id(self.wal), seq=seq, status=502,
                          applied=applied)
                result = router._partial_write(failed_names or "unknown")
        self._maybe_compact()
        return result

    # -- per-shard WAL compaction / backlog bound -------------------------

    def _maybe_compact(self) -> None:
        """Advance this shard's log past the min-applied watermark once
        it has grown past a quarter of its bound; a laggard that would
        pin it past the bound goes STALE (the automated resync streams
        it fragments instead) so the backlog stays bounded.  In-flight
        resync rounds FLOOR the watermark at their seed sequence."""
        router = self.router
        if self.wal.size_bytes <= max(self.wal.max_bytes // 4, 1 << 16):
            return
        while True:
            with self._mu:
                tracked = [g for g in self.groups if not g.stale]
                floors = list(self._resync_floor.values())
                snapshot = {g.name: g.applied_seq for g in tracked}
            if not tracked and not floors:
                spec.emit("compact_plan", src=id(self.wal),
                          floor=self.wal.last_seq, tracked={}, floors=[])
                self.wal.compact(self.wal.last_seq)
                return
            min_applied = min(
                [g.applied_seq for g in tracked] + floors
            )
            spec.emit("compact_plan", src=id(self.wal), floor=min_applied,
                      tracked=snapshot, floors=floors)
            self.wal.compact(min_applied)
            if self.wal.size_bytes <= self.wal.max_bytes:
                return
            laggards = [
                g for g in tracked
                if g.applied_seq == min_applied and g.applied_seq < self.wal.last_seq
            ]
            if not laggards:
                return  # the head itself exceeds the bound; nothing to drop
            for g in laggards:
                self.stats.count(f"replica.stale.{g.name}")
                self.stats.set(
                    "replica.last_failure",
                    f"{g.name}: lag exceeded wal-max-bytes; marked stale "
                    "(automated resync scheduled)",
                )
                router._mark_unhealthy(g, "stale: WAL compacted past its lag")
                with self._mu:
                    # Stale groups stay in the probe rotation at the MAX
                    # interval — the automated resync's (and a hand-
                    # resynced group's) live door back in; PR 7 dropped
                    # them from probing forever.
                    g.stale = True
                    g.probe_delay = router.probe_max_interval_s
                    g.probe_at = time.monotonic() + g.probe_delay * router._rng.uniform(0.5, 1.0)

    def wal_json(self) -> dict:
        return {
            "firstSeq": self.wal.first_seq,
            "lastSeq": self.wal.last_seq,
            "bytes": self.wal.size_bytes,
            "durable": self.wal.path is not None,
        }

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "slices": {"lo": self.lo, "hi": self.hi},
            "writeSeq": self.write_seq,
            "quorum": self.quorum,
            "quorate": self.quorate(),
            "groups": [g.name for g in self.groups],
            "wal": self.wal_json(),
        }


@lockcheck.guarded_class
class ReplicaRouter:
    """HTTP front door fanning reads over replica serving groups."""

    # /debug/fleet's scrape cache is shared between handler threads.
    # (The write-sequence high-water marks moved to ShardRuntime with
    # the sequence spaces themselves — see its _guarded_by_.)
    _guarded_by_ = {
        "_fleet_cache": "replica.router._fleet_mu",
    }

    def __init__(
        self,
        groups=None,
        host: str = "127.0.0.1",
        port: int = 0,
        failover: bool = True,
        default_deadline_ms: float = 0.0,
        timeout: float = 30.0,
        probe_interval_s: float = 1.0,
        probe_max_interval_s: float = 30.0,
        wal: Optional[WriteAheadLog] = None,
        faults: Optional[FaultInjector] = None,
        stats=None,
        tracer=None,
        anti_entropy_interval_s: float = 0.0,
        resync_chunk_bytes: int = 256 << 10,
        resync_columnar: bool = False,
        shard_map: Optional[ShardMap] = None,
        wal_dir: Optional[str] = None,
        wal_max_bytes: Optional[int] = None,
        admission=None,
        tenancy=None,
    ):
        if shard_map is None:
            if not groups:
                raise ValueError("replica router needs at least one group")
            shard_map = single_shard_map(list(groups))
        elif groups:
            raise ValueError(
                "pass groups through the shard map, not both arguments"
            )
        self.host = host
        self.port = port
        self.failover = failover
        self.default_deadline_ms = default_deadline_ms
        self.timeout = timeout
        self.probe_interval_s = probe_interval_s
        self.probe_max_interval_s = probe_max_interval_s
        self.stats = stats if stats is not None else NOP_STATS
        self.tracer = tracer
        # [tenancy]: weighted fair-share admission at the ROUTER door —
        # the same class doors the per-server handler runs, so a hostile
        # tenant flooding the fleet front door sheds at ITS share before
        # its requests ever fan out to a group.  None (the default)
        # keeps the routed path byte-identical to the pre-tenancy
        # router: no door, no extra lock hop.
        self.tenancy = tenancy
        self.admission = admission
        self.faults = faults if faults is not None else (
            FaultInjector.from_env() or NOP_FAULTS
        )
        self.resync_chunk_bytes = resync_chunk_bytes
        # Router-local adaptive-budget loop (planner.AdaptiveBudgets over
        # a router-local CostLedger): catch-up replay and resync push
        # costs observed by the managers feed back into the drain-batch
        # and chunk sizes they use next round.  Same gate as serve-side
        # cost accounting (PILOSA_TPU_COSTS) so a cost-free deploy stays
        # cost-free here too; the static knobs above remain the floor
        # and the fallback.
        self.budgets = None
        if costs_mod.enabled_from_env():
            from pilosa_tpu import planner as planner_mod

            self.budgets = planner_mod.AdaptiveBudgets(
                costs_mod.CostLedger(stats=self.stats),
                resync_chunk_bytes=resync_chunk_bytes,
                stats=self.stats,
            )
        # Columnar resync negotiation: movers may fetch a fragment the
        # laggard lacks ENTIRELY as Arrow record batches and push it
        # through the laggard's device-build /bulk door (the bulk OR
        # equals replacement only over an empty target); any refusal on
        # either side degrades to the roaring byte stream.
        self.resync_columnar = resync_columnar
        # Where NEW shard WALs land (auto-split maps, live resharding);
        # None keeps them in-memory like the default single WAL.
        self._wal_dir = wal_dir
        self._wal_max_bytes = wal_max_bytes
        # Cross-group anti-entropy sweep cadence (0 = off, the test
        # default): healthy groups' digests compared, divergence counted
        # + logged + repaired from the majority copy.
        self.anti_entropy_interval_s = anti_entropy_interval_s
        # Bound on one sweep's repair work under the sequencer lock.
        self.anti_entropy_budget_s = 30.0
        self._mu = lockcheck.named_lock("replica.router._mu")  # group table (health/inflight/epoch)
        # /debug/fleet scrape cache: the last SUCCESSFUL per-group scrape
        # keeps serving (stamped stale, with its age) while a group is
        # down, so the fleet view degrades to partial instead of losing
        # the dead group entirely.
        self._fleet_mu = lockcheck.named_lock("replica.router._fleet_mu")
        self._fleet_cache: dict[str, dict] = {}
        self._rng = random.Random()  # probe jitter (timing only)
        # THE ROUTING GATE: live resharding flips the shard map behind
        # an epoch fence — new routed requests wait at the gate while
        # the flip drains the in-flight ones, so no read can observe a
        # moved slice range on both its old and new owner.  The gate's
        # lock is only ever held to flip flags and count — never across
        # a socket.
        self._gate_cv = lockcheck.named_condition("replica.router._route_gate")
        self._active_routed = 0
        self._gated = False
        self._httpd = None
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # Build the shard runtimes: each shard gets its own GroupStates,
        # its own WAL, and its own sequencer (see ShardRuntime).  An
        # explicitly passed ``wal`` belongs to shard 0 — the single-
        # shard (default) layout, where it is THE router WAL.
        self.shard_map = shard_map
        self.map_epoch = 0
        self.shards: list = []
        self.groups: list = []
        self._group_shard: dict = {}
        gi = 0
        for si, sh in enumerate(shard_map):
            gs = []
            for spec_s in sh.group_specs:
                gs.append(_parse_group_spec(gi, spec_s))
                gi += 1
            swal = wal if si == 0 and wal is not None else self._shard_wal(sh.name)
            rt = ShardRuntime(self, sh, gs, swal)
            self.shards.append(rt)
            self.groups.extend(gs)
            for g in gs:
                self._group_shard[g] = rt
        if len({g.name for g in self.groups}) != len(self.groups):
            raise ValueError("duplicate replica group names")
        # Single-shard compat aliases: tests, operators, and the CLI all
        # reach the sequencing state through the router object — shard 0
        # IS that state under the default map (same WAL object, same
        # lock instance, same floor dict), so the pre-shard surface
        # stays byte-for-byte.
        s0 = self.shards[0]
        self.wal = s0.wal
        self.catchup = s0.catchup
        self.resync = s0.resync
        self._seq_mu = s0._seq_mu
        self._resync_floor = s0._resync_floor
        for g in self.groups:
            self.stats.gauge(f"replica.healthy.{g.name}", 1)
            self.stats.gauge(f"replica.inflight.{g.name}", 0)
            self.stats.gauge(f"replica.lag.{g.name}", 0)
        self.stats.gauge("replica.shard.count", len(self.shards))
        self.stats.gauge("replica.shard.map_epoch", self.map_epoch)

    def _shard_wal(self, shard_name: str) -> WriteAheadLog:
        """A shard's write log: durable under ``wal_dir`` (one file per
        shard — sequence spaces never mix), in-memory otherwise (same
        sequencing/abort/replay semantics, no crash durability)."""
        path = None
        if self._wal_dir:
            path = os.path.join(
                os.path.expanduser(self._wal_dir), f"router-{shard_name}.wal"
            )
        kw = {}
        if self._wal_max_bytes is not None:
            kw["max_bytes"] = self._wal_max_bytes
        return WriteAheadLog(path, stats=self.stats, faults=self.faults, **kw)

    # -- group table ------------------------------------------------------

    @property
    def quorum(self) -> int:
        """Shard 0's majority — THE quorum under the default single-
        shard map (multi-shard maps report per-shard quorums in
        /replica/status's shards array)."""
        return self.shards[0].quorum

    @property
    def write_seq(self) -> int:
        """Shard 0's write high-water mark (the router-wide mark under
        the default single-shard map)."""
        return self.shards[0].write_seq

    def _shard_for_slice(self, slice_i: int):
        """The ShardRuntime owning ``slice_i`` (positional: runtimes
        mirror the validated map's order)."""
        sh = self.shard_map.shard_of(slice_i)
        for rt in self.shards:
            if rt.name == sh.name:
                return rt
        raise ShardMapError(f"no runtime for shard {sh.name}")  # unreachable

    def _ready_groups(self) -> list:
        """Groups in the write rotation, across every shard."""
        out = []
        for sh in self.shards:
            out.extend(sh._ready_groups())
        return out

    def _pick(self, exclude=None) -> Optional[GroupState]:
        """Shard 0's read pick (single-shard compat; multi-shard reads
        pick per target shard in _route_read)."""
        return self.shards[0]._pick(exclude=exclude)

    def _release(self, g: GroupState) -> None:
        with self._mu:
            g.inflight -= 1
            self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)

    def _mark_unhealthy(self, g: GroupState, why: str) -> None:
        with self._mu:
            first = g.healthy
            g.healthy = False
            # Arm the probe backoff: first retry after the base
            # interval, doubling (with jitter) on every failed probe.
            if first:
                g.probe_delay = self.probe_interval_s
                g.probe_at = time.monotonic() + g.probe_delay * self._rng.uniform(0.5, 1.0)
        if not first:
            return
        self.stats.gauge(f"replica.healthy.{g.name}", 0)
        self.stats.count(f"replica.unhealthy.{g.name}")
        self.stats.set("replica.last_failure", f"{g.name}: {why}")

    def _mark_healthy(self, g: GroupState) -> None:
        with self._mu:
            if g.healthy:
                return
            g.healthy = True
            g.probe_delay = self.probe_interval_s
        self.stats.gauge(f"replica.healthy.{g.name}", 1)
        self.stats.count("replica.recovered")

    def _mark_lagging(self, g: GroupState) -> None:
        """The group missed a sequenced write: out of the read rotation
        until catch-up replays it to its shard's WAL head."""
        sh = self._group_shard.get(g)
        (sh if sh is not None else self.shards[0])._mark_lagging(g)

    def _backoff(self, g: GroupState) -> None:
        """One failed probe: double the group's retry delay (jittered,
        capped) so a dead group is not hammered in lockstep."""
        with self._mu:
            g.probe_delay = min(
                self.probe_max_interval_s,
                max(self.probe_interval_s, g.probe_delay * 2.0),
            )
            g.probe_at = time.monotonic() + g.probe_delay * self._rng.uniform(0.5, 1.5)

    def _note_epoch(self, g: GroupState, hdr: Optional[str]) -> None:
        """Track the group identity header; a changed epoch means the
        group restarted (in-memory generation vectors rebuilt) — counted
        so dashboards can correlate it with that group's cold caches.
        Called from every forward path (handler threads, probe thread),
        so the epoch write takes the table lock like any other
        GroupState mutation."""
        if not hdr:
            return
        with self._mu:
            bumped = g.epoch is not None and g.epoch != hdr
            g.epoch = hdr
        if bumped:
            self.stats.count("replica.epoch_bump")

    def _note_applied(self, g: GroupState, hdr: Optional[str]) -> None:
        """Passive lag tracking: every group response reports its
        applied sequence high-water mark.  The monotonic-max update is
        a read-modify-write, so it must hold the table lock — two
        concurrent responses would otherwise drop the higher mark."""
        if not hdr:
            return
        try:
            seq = int(hdr)
        except ValueError:
            return
        sh = self._group_shard.get(g)
        wal = sh.wal if sh is not None else self.wal
        with self._mu:
            g.applied_seq = max(g.applied_seq, seq)
            applied = g.applied_seq
            spec.emit("mark", src=id(wal), group=g.name,
                      epoch=g.epoch, value=applied)
        self.stats.gauge(
            f"replica.lag.{g.name}", max(0, wal.last_seq - applied)
        )

    def healthy_count(self) -> int:
        with self._mu:
            return sum(1 for g in self.groups if g.healthy)

    def quorate(self) -> bool:
        """True when writes can commit EVERYWHERE: every shard has at
        least a MAJORITY of its group set in rotation (healthy + caught
        up + not stale).  Minority outages degrade durability of the
        margin, not availability — each shard's WAL replays the missed
        suffix to its laggards."""
        return all(sh.quorate() for sh in self.shards)

    # -- the hop ----------------------------------------------------------

    def _forward(self, g: GroupState, method: str, path_qs: str, body: bytes,
                 headers: dict, deadline=None, trace_id: str = "",
                 extra_headers: Optional[dict] = None,
                 timeout_s: Optional[float] = None):
        """One HTTP exchange with a group.  Returns (status, ctype,
        payload, response headers); raises OSError on a connect/transport
        failure (the caller's failover trigger).  ``extra_headers``
        carries router-owned headers (write sequence, replay marker);
        ``timeout_s`` tightens the socket below ``self.timeout`` (the
        locked catch-up drain's per-record bound)."""
        try:
            self.faults.hit("forward", key=g.name)
        except InjectedStatus as e:
            rh = {"Retry-After": "0.250"} if e.status in (429, 503) else {}
            return (
                e.status, "application/json",
                json.dumps({"error": str(e)}).encode(), rh,
            )
        fwd = {k: v for k, v in headers.items() if k.lower() not in _HOP_HEADERS}
        timeout = self.timeout
        if timeout_s is not None:
            timeout = min(timeout, max(timeout_s, 0.001))
        if deadline is not None:
            # Hop rule (qos/deadline.py): forward the REMAINING budget,
            # tighten the socket to match (+1s for the 504 to travel).
            fwd[DEADLINE_HEADER] = deadline.header_value()
            timeout = min(timeout, deadline.remaining_ms() / 1000.0 + 1.0)
        if trace_id:
            fwd[TRACE_HEADER] = trace_id
        if extra_headers:
            fwd.update(extra_headers)
        req = urllib.request.Request(
            g.base + path_qs, data=body if body else None, method=method
        )
        for k, v in fwd.items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                status, payload, rheaders = resp.status, resp.read(), resp.headers
        except urllib.error.HTTPError as e:
            status, payload, rheaders = e.code, e.read(), e.headers
        except urllib.error.URLError as e:
            # Normalize to OSError for the failover path (URLError wraps
            # the socket-level reason).
            raise OSError(str(e.reason))
        self._note_epoch(g, rheaders.get(GROUP_HEADER))
        self._note_applied(g, rheaders.get(APPLIED_SEQ_HEADER))
        return status, rheaders.get("Content-Type", "application/json"), payload, rheaders

    # -- read path --------------------------------------------------------

    @staticmethod
    def _slices_param(query: str) -> Optional[list]:
        """The ``slices=`` query parameter as an int list (None when
        absent or malformed — malformed means "all slices", the safe
        over-approximation, never a 400 on the read path)."""
        vals = parse_qs(query).get("slices")
        if not vals:
            return None
        try:
            return [int(s) for s in vals[0].split(",") if s.strip()]
        except ValueError:
            return None

    def _read_targets(self, path: str, query: str, headers: dict):
        """The shards a read must touch.  Single-shard maps (the
        default) short-circuit to shard 0; multi-shard maps compute the
        slice cover: a ``slices=`` query param fans only to the owners
        of those slices (exact and minimal — K shards cost exactly K
        forwards), an unscoped query spans the whole slice space, and
        slice-addressed fragment reads go to the one owner."""
        if len(self.shards) == 1:
            return [self.shards[0]]
        if path == "/fragment/data":
            vals = parse_qs(query).get("slice")
            if vals:
                try:
                    return [self._shard_for_slice(int(vals[0]))]
                except ValueError:
                    pass
            return [self.shards[0]]
        if _QUERY_PATH_RE.match(path):
            slices = self._slices_param(query)
            if slices is None:
                return list(self.shards)
            cover = self.shard_map.cover(slices)
            return [sh for sh in self.shards if sh.name in cover]
        if path == "/slices/max":
            return list(self.shards)
        # Schema/status/admin reads: identical on every shard (mutating
        # admin fans to all of them) — any one shard answers.
        return [self.shards[0]]

    def _route_read(self, method: str, path_qs: str, body: bytes, headers: dict,
                    deadline=None, trace=None):
        parsed = urlparse(path_qs)
        targets = self._read_targets(parsed.path, parsed.query, headers)
        if not targets:
            # An empty cover (slices= named no slice any shard owns is
            # impossible — the map is total — but an empty list is):
            # nothing to scan, an empty result.
            return 200, "application/json", b'{"results": []}', {}
        if len(targets) == 1:
            return self._route_read_one(targets[0], method, path_qs, body,
                                        headers, deadline=deadline, trace=trace)
        if "application/x-protobuf" in (headers.get("accept") or ""):
            return (
                501, "application/json",
                json.dumps({"error": "protobuf responses cannot be merged "
                            "across shards; use JSON or scope the query "
                            "with slices="}).encode(), {},
            )
        outs = []
        for sh in targets:
            out = self._route_read_one(sh, method, path_qs, body, headers,
                                       deadline=deadline, trace=trace)
            if out[0] >= 300:
                return out  # one shard's failure is the read's failure
            outs.append(out)
        self.stats.count("replica.shard.read_fanout")
        if parsed.path == "/slices/max":
            merged: dict = {}
            for _st, _ct, payload, _h in outs:
                try:
                    for idx, mx in (json.loads(payload).get("maxSlices") or {}).items():
                        merged[idx] = max(merged.get(idx, 0), int(mx))
                except (ValueError, TypeError):
                    pass
            body_out = json.dumps({"maxSlices": merged}).encode()
        else:
            body_out = _merge_query_payloads([o[2] for o in outs])
        return 200, "application/json", body_out, {GROUP_HEADER: "all"}

    def _route_read_one(self, sh, method: str, path_qs: str, body: bytes,
                        headers: dict, deadline=None, trace=None):
        g = sh._pick()
        if g is None:
            return self._shed(
                503, f"no healthy replica group in shard {sh.name}",
                retry_after=1.0,
            )
        attempt, first, last = 0, g, g
        while True:
            last = g
            sp = trace.root.child("forward") if trace is not None else None
            try:
                out = self._forward(
                    g, method, path_qs, body, headers, deadline=deadline,
                    trace_id=(trace.id if trace is not None else ""),
                )
            except OSError as e:
                self._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, error=str(e))
                self._mark_unhealthy(g, str(e))
                out = None
            else:
                self._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, status=out[0])
                    raw = out[3].get(TRACE_SPANS_HEADER)
                    if raw:
                        try:
                            sp.graft(json.loads(raw))
                        except ValueError:
                            pass
                if out[0] < 500 or out[0] == 504:
                    # <500 is an answer; 504 is deadline-exceeded for
                    # THIS request's own budget — request-scoped, not a
                    # group-health signal, so it must never demote the
                    # group (a burst of tight-deadline reads would
                    # otherwise mark every group unhealthy and refuse
                    # all writes via the quorum rule).
                    if trace is not None:
                        trace.root.tags["group"] = g.name
                    extra = {GROUP_HEADER: out[3].get(GROUP_HEADER) or g.name}
                    ra = out[3].get("Retry-After")
                    if ra:
                        extra["Retry-After"] = ra
                    return out[0], out[1], out[2], extra
                # Other 5xx: this group cannot serve; a degraded
                # lockstep group answers 503 until its job restarts, so
                # stop routing reads there and let the probe restore it.
                self._mark_unhealthy(g, f"HTTP {out[0]} on read")
            # One-shot failover: reads are side-effect-free, so the
            # retry on a sibling (of the SAME shard — only it holds the
            # slices) is always safe.
            if not self.failover or attempt >= 1:
                break
            attempt += 1
            g = sh._pick(exclude=first)
            if g is None:
                break
            self.stats.count("replica.failover")
        if out is not None:
            return out[0], out[1], out[2], {GROUP_HEADER: last.name}
        return self._shed(503, "replica group unreachable", retry_after=1.0)

    # -- write path -------------------------------------------------------

    def _route_write(self, method: str, path_qs: str, body: bytes, headers: dict,
                     deadline=None, trace=None, fan_admin: bool = False):
        """Route one write.  A single-shard map (the default) sequences
        straight into shard 0 — the pre-shard fast path, byte-for-byte
        the old router.  A multi-shard map routes by slice ownership:

        - mutating ADMIN (schema, deletions) fans to EVERY shard —
          replicated schema must stay identical across the whole mesh;
        - ``/fragment/data`` posts route by their ``slice=`` param;
        - PQL write bodies route by ``columnID // SLICE_WIDTH``: one
          owning shard sequences the whole body, a body spanning shards
          is SPLIT into per-shard sub-batches (each sequenced in its
          owner's space, results reassembled in call order), and
          column-free calls (SetRowAttrs — row metadata lives
          everywhere) broadcast to all shards;
        - streaming ingest (``/import``, restore) and bodies mixing
          reads with multi-shard writes answer 501 — they cannot be
          slice-routed; scope them per shard or run a single-shard map
          (documented in DEVELOPMENT.md).

        Two shards' sequencers are DIFFERENT lock instances, so their
        fan-outs run concurrently — write throughput scales with the
        shard axis."""
        if len(self.shards) == 1:
            return self.shards[0].sequence_write(
                method, path_qs, body, headers, deadline=deadline, trace=trace
            )
        parsed = urlparse(path_qs)
        if fan_admin:
            return self._sequence_all(method, path_qs, body, headers,
                                      deadline=deadline, trace=trace)
        if parsed.path == "/fragment/data":
            vals = parse_qs(parsed.query).get("slice")
            if vals:
                try:
                    sh = self._shard_for_slice(int(vals[0]))
                except (ValueError, ShardMapError):
                    sh = None
                if sh is not None:
                    return sh.sequence_write(method, path_qs, body, headers,
                                             deadline=deadline, trace=trace)
        if _QUERY_PATH_RE.match(parsed.path):
            return self._route_query_write(method, path_qs, body, headers,
                                           deadline=deadline, trace=trace)
        self.stats.count("replica.shard.unroutable")
        return (
            501, "application/json",
            json.dumps({"error": f"{method} {parsed.path} cannot be routed "
                        "across a partitioned shard map; address one shard's "
                        "slice range or run a single-shard layout"}).encode(),
            {},
        )

    def _route_query_write(self, method: str, path_qs: str, body: bytes,
                           headers: dict, deadline=None, trace=None):
        """Slice-route a PQL write body under a multi-shard map (see
        _route_write's routing table)."""
        try:
            q = pql.parse_cached(body.decode("utf-8"))
        except (pql.ParseError, UnicodeDecodeError):
            # Unparsable bodies 400 deterministically wherever they
            # land: shard 0 sequences it and the deterministic-4xx rule
            # tombstones the record.
            return self.shards[0].sequence_write(
                method, path_qs, body, headers, deadline=deadline, trace=trace
            )
        by_shard: dict = {}  # shard name -> original call indexes
        broadcast = False
        for i, call in enumerate(q.calls):
            if call.name not in WRITE_CALL_NAMES:
                # A read mixed into a multi-shard write body would need
                # its result merged ACROSS shards mid-sequence — refuse
                # rather than answer it from one shard's slice subset.
                self.stats.count("replica.shard.unroutable")
                return (
                    501, "application/json",
                    json.dumps({"error": f"call {call.name} mixes reads into "
                                "a write body; multi-shard maps require "
                                "write-only bodies on the write path"}).encode(),
                    {},
                )
            if call.name == "SetRowAttrs":
                broadcast = True  # row metadata lives on every shard
                continue
            try:
                col, ok = call.uint_arg("columnID")
            except TypeError:
                ok = False
            if not ok:
                self.stats.count("replica.shard.unroutable")
                return (
                    501, "application/json",
                    json.dumps({"error": f"call {call.name} carries no integer "
                                "columnID; custom column labels are not "
                                "slice-routable — use a single-shard map"}).encode(),
                    {},
                )
            sh = self._shard_for_slice(col // SLICE_WIDTH)
            by_shard.setdefault(sh.name, []).append(i)
        if broadcast and by_shard:
            self.stats.count("replica.shard.unroutable")
            return (
                501, "application/json",
                json.dumps({"error": "body mixes broadcast calls "
                            "(SetRowAttrs) with column-routed writes; send "
                            "them as separate requests"}).encode(),
                {},
            )
        if broadcast:
            return self._sequence_all(method, path_qs, body, headers,
                                      deadline=deadline, trace=trace)
        if len(by_shard) == 1:
            sh = self._shard_by_name(next(iter(by_shard)))
            return sh.sequence_write(method, path_qs, body, headers,
                                     deadline=deadline, trace=trace)
        # SPLIT: per-shard sub-batches in deterministic shard order,
        # each sequenced in its owner's space; results reassembled in
        # the original call order.  A failed sub-batch surfaces its
        # error — already-committed shards keep theirs, and the client's
        # idempotent retry realigns the rest.
        self.stats.count("replica.shard.split_writes")
        results: list = [None] * len(q.calls)
        last = None
        for name in sorted(by_shard):
            sh = self._shard_by_name(name)
            idxs = by_shard[name]
            sub = " ".join(str(q.calls[i]) for i in idxs).encode()
            out = sh.sequence_write(method, path_qs, sub, headers,
                                    deadline=deadline, trace=trace)
            if out[0] >= 300:
                return out
            try:
                rs = json.loads(out[2]).get("results") or []
            except (ValueError, AttributeError):
                rs = []
            for k, i in enumerate(idxs):
                results[i] = rs[k] if k < len(rs) else None
            last = out
        return (
            200, last[1] if last else "application/json",
            json.dumps({"results": results}).encode(),
            {GROUP_HEADER: "all"},
        )

    def _shard_by_name(self, name: str):
        for sh in self.shards:
            if sh.name == name:
                return sh
        raise ShardMapError(f"no runtime for shard {name}")

    def _sequence_all(self, method: str, path_qs: str, body: bytes,
                      headers: dict, deadline=None, trace=None):
        """Sequence one write into EVERY shard (mutating admin,
        broadcast PQL): each shard's own sequencer orders it against
        that shard's writes.  The first failing shard's answer surfaces
        — shards that already committed keep the write (idempotent
        re-apply is the contract), and the retry realigns the rest."""
        out = None
        for sh in self.shards:
            out = sh.sequence_write(method, path_qs, body, headers,
                                    deadline=deadline, trace=trace)
            if out[0] >= 300:
                return out
        self.stats.count("replica.shard.fanout_writes")
        return out

    def _partial_write(self, failed_names: str):
        """A write reached fewer than a majority of groups: 502 tells
        the client it may be partially applied — the WAL record stays,
        the lagging groups replay it during catch-up, and the
        idempotent client retry is harmless either way."""
        return (
            502,
            "application/json",
            json.dumps({
                "error": f"write failed on group(s) {failed_names}; "
                "may be partially applied — retry when the group set is quorate"
            }).encode(),
            {"Retry-After": "1.000"},
        )

    def _shed(self, status: int, message: str, retry_after: float = 1.0):
        """A router-door refusal (non-quorate write, no healthy group,
        WAL failure).  The Retry-After hint carries DECORRELATED JITTER
        (mirroring the client-side retry budget's jitter, PR 7): a
        fixed hint makes a synchronized client herd retry in lockstep
        against a recovering cluster — the exact moment it can least
        absorb a coordinated burst.  Jitter here spreads even clients
        that obey the hint literally."""
        jittered = max(0.05, self._rng.uniform(retry_after * 0.5,
                                               retry_after * 1.5))
        return (
            status,
            "application/json",
            json.dumps({"error": message}).encode(),
            {"Retry-After": f"{jittered:.3f}"},
        )

    # -- WAL compaction / backlog bound -----------------------------------

    def _maybe_compact(self) -> None:
        """Per-shard compaction (see ShardRuntime._maybe_compact —
        each shard's log advances past ITS min-applied watermark)."""
        for sh in self.shards:
            sh._maybe_compact()

    # -- dispatch ---------------------------------------------------------

    def handle(self, method: str, path_qs: str, body: bytes, headers: dict):
        """Serve one request.  Returns (status, ctype, payload, extra
        headers).  ``headers`` keys must be lowercase."""
        parsed = urlparse(path_qs)
        path = parsed.path
        if method == "GET" and path == "/debug/vars":
            snap = self.stats.snapshot() if hasattr(self.stats, "snapshot") else {}
            return 200, "application/json", (json.dumps(snap) + "\n").encode(), {}
        if method == "GET" and path == "/metrics":
            return (
                200, metrics_mod.CONTENT_TYPE,
                metrics_mod.render(self.stats).encode(), {},
            )
        if method == "GET" and path == "/debug/traces":
            return self._debug_traces(parse_qs(parsed.query))
        if method == "GET" and path == "/debug/fleet":
            return self._debug_fleet(parse_qs(parsed.query))
        if method == "GET" and path == "/replica/status":
            return self._replica_status()
        if method == "POST" and path == "/replica/reshard":
            # Router-owned admin: operates the routing gate itself, so
            # it must never pass THROUGH the gate.
            return self._handle_reshard(body)

        deadline = qos.deadline_from_headers(headers, self.default_deadline_ms)
        if deadline is not None and deadline.expired():
            return (
                504, "application/json",
                json.dumps({"error": "deadline exceeded (router)"}).encode(), {},
            )
        cls = qos.classify_request(method, path, body)
        # [tenancy]: the router-door fair-share gate.  The tenant is
        # resolved through the SAME seam the handler and the lockstep
        # front end use (header > map > index name > default), and the
        # door is the same AdmissionController the servers run — an
        # over-share tenant sheds 429+Retry-After HERE, before its
        # request costs a single group-side socket.
        tenant = None
        if self.admission is not None:
            if self.tenancy is not None:
                tenant = self.tenancy.resolve(path, headers)
            try:
                self.admission.acquire(cls, deadline, tenant=tenant)
            except qos.ShedError as e:
                self.stats.count("replica.router.shed")
                return self._shed(e.status, str(e), retry_after=e.retry_after)
        try:
            return self._handle_routed(
                method, path_qs, path, body, headers, deadline, cls
            )
        finally:
            if self.admission is not None:
                self.admission.release(cls, tenant=tenant)

    def _handle_routed(self, method, path_qs, path, body, headers,
                       deadline, cls):
        """The routed section of ``handle`` — everything past the
        tenancy door (the door must release on EVERY exit path)."""
        # Mutating admin (schema, deletions) must apply to EVERY group or
        # the replicas' schemas diverge; admin GETs route like reads.
        fan_all = cls == qos.CLASS_WRITE or (
            cls == qos.CLASS_ADMIN and method in ("POST", "DELETE", "PATCH")
        )
        trace = (
            self.tracer.begin(headers, name=f"{method} {path}")
            if self.tracer is not None
            else None
        )
        t0 = time.perf_counter()
        # Every routed request crosses the gate: an in-flight reshard
        # flip holds newcomers here (bounded — the fence is a drain plus
        # a delta stream, not a full copy) so no request can observe two
        # owners for one slice.  Ungated state (the steady state) costs
        # two uncontended lock hops.
        self._gate_enter()
        try:
            if fan_all:
                out = self._route_write(
                    method, path_qs, body, headers, deadline=deadline,
                    trace=trace, fan_admin=(cls == qos.CLASS_ADMIN),
                )
            else:
                out = self._route_read(method, path_qs, body, headers,
                                       deadline=deadline, trace=trace)
        finally:
            self._gate_exit()
        if self.tracer is not None:
            extra = self.tracer.finish_request(
                trace, name=f"{method} {path}",
                dt_ms=(time.perf_counter() - t0) * 1e3,
                body=body, status=out[0],
            )
            if extra:
                merged = dict(out[3])
                merged.update(extra)
                out = (out[0], out[1], out[2], merged)
        return out

    def _gate_enter(self) -> None:
        with self._gate_cv:
            while self._gated:
                self._gate_cv.wait(timeout=30.0)
            self._active_routed += 1

    def _gate_exit(self) -> None:
        with self._gate_cv:
            self._active_routed -= 1
            self._gate_cv.notify_all()

    def _replica_status(self):
        with self._mu:
            table = [g.to_json() for g in self.groups]
            heads = {sh.name: sh.wal.last_seq for sh in self.shards}
        shard_of = {g.name: self._group_shard[g].name for g in self.groups}
        for t in table:
            # Lag is measured against the group's OWN shard's head —
            # cross-shard sequence numbers are unrelated.
            t["shard"] = shard_of.get(t["name"])
            t["lag"] = max(0, heads.get(t["shard"], 0) - t["appliedSeq"])
        payload = json.dumps({
            "groups": table,
            "quorate": self.quorate(),
            "quorum": self.quorum,
            "write_seq": self.write_seq,
            "wal": self.shards[0].wal_json(),
            "mapEpoch": self.map_epoch,
            "shards": [sh.to_json() for sh in self.shards],
        }).encode()
        return 200, "application/json", payload, {}

    def _debug_traces(self, params: dict):
        if self.tracer is None:
            return 200, "application/json", b'{"traces": []}\n', {}
        # Malformed/out-of-range filters clamp to defaults — a debug
        # endpoint must answer, not 400 (same contract as the handler).
        min_ms = metrics_mod.clamp_float((params.get("min-ms") or [None])[0], 0.0)
        limit = metrics_mod.clamp_int((params.get("limit") or [None])[0], 64)
        payload = json.dumps(
            {"traces": self.tracer.traces_json(min_ms=min_ms, limit=limit)}
        ).encode()
        return 200, "application/json", payload, {}

    # -- /debug/fleet: the cluster-wide observability view ----------------

    def _scrape_group(self, base: str, timeout_s: float):
        """One group scrape: /replica/health (authoritative liveness +
        applied sequence) and /debug/vars (the group's own stats
        snapshot).  Returns (scrape dict, None) on success or
        (None, error string) when the health probe fails; a vars
        failure degrades to health-only rather than failing the
        scrape."""
        out: dict = {}
        try:
            req = urllib.request.Request(base + "/replica/health", method="GET")
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                out["health"] = json.loads(resp.read() or b"{}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            return None, f"health: {e}"
        try:
            req = urllib.request.Request(base + "/debug/vars", method="GET")
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                vars_snap = json.loads(resp.read() or b"{}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            vars_snap = {}
            out["varsError"] = str(e)
        out["appliedSeq"] = out["health"].get("appliedSeq")
        # Latency percentiles ride the group's qos.latency_ms.<class>
        # histograms; the rest of the snapshot is served verbatim.
        out["latencyMs"] = {
            key.split("qos.latency_ms.", 1)[1]: val
            for key, val in vars_snap.items()
            if key.startswith("qos.latency_ms.") and isinstance(val, dict)
        }
        # Per-tenant rows off the group's own counters: every
        # tenancy.<series>.<tenant> key pivots into tenant -> series so
        # the fleet view answers "which tenant is this group shedding"
        # without a per-group scrape by the operator.
        tenants: dict = {}
        for key, val in vars_snap.items():
            if not key.startswith("tenancy."):
                continue
            rest = key.split("tenancy.", 1)[1]
            series, _, tenant = rest.partition(".")
            if tenant:
                tenants.setdefault(tenant, {})[series] = val
        out["tenants"] = tenants
        out["vars"] = vars_snap
        return out, None

    def _debug_fleet(self, params: dict):
        """Aggregate every group's stats/health/applied-seq plus the
        router's own WAL + resync/anti-entropy progress into one
        cluster-wide JSON view.  A down group yields a PARTIAL entry:
        the router-side table row, the error, and the last successful
        scrape (if any) stamped with its age."""
        timeout_s = metrics_mod.clamp_float(
            (params.get("timeout-ms") or [None])[0], 750.0, lo=50.0, hi=10_000.0
        ) / 1e3
        now = time.time()
        with self._mu:
            table = {g.name: g.to_json() for g in self.groups}
            heads = {sh.name: sh.wal.last_seq for sh in self.shards}
            # Shard-qualified floors (single-shard keeps bare group
            # names — the pre-shard payload shape).
            if len(self.shards) == 1:
                floors = dict(self._resync_floor)
            else:
                floors = {
                    f"{sh.name}/{gname}": seq
                    for sh in self.shards
                    for gname, seq in sh._resync_floor.items()
                }
        shard_of = {g.name: self._group_shard[g].name for g in self.groups}
        groups_out = []
        scraped_ok = 0
        for name, row in table.items():
            entry = dict(row)
            entry["shard"] = shard_of.get(name)
            # Per-(shard, group) WAL depth: committed records of ITS
            # shard this group has not applied yet (what catch-up will
            # replay to it).
            entry["walDepth"] = max(
                0, heads.get(entry["shard"], 0) - entry["appliedSeq"]
            )
            scrape, err = self._scrape_group(entry["base"], timeout_s)
            if scrape is not None:
                scrape["scrapedAt"] = round(now, 3)
                with self._fleet_mu:
                    self._fleet_cache[name] = scrape
                scraped_ok += 1
            else:
                entry["error"] = err
                with self._fleet_mu:
                    scrape = self._fleet_cache.get(name)
            if scrape is not None:
                entry["scrape"] = scrape
                entry["scrapedAt"] = scrape["scrapedAt"]
                entry["ageMs"] = round(max(0.0, (now - scrape["scrapedAt"]) * 1e3), 1)
            else:
                entry["scrape"] = None
                entry["scrapedAt"] = None
                entry["ageMs"] = None
            entry["staleScrape"] = "error" in entry
            groups_out.append(entry)
        router_stats = (
            self.stats.snapshot() if hasattr(self.stats, "snapshot") else {}
        )
        payload = {
            "ts": round(now, 3),
            "quorum": self.quorum,
            "quorate": self.quorate(),
            "writeSeq": self.write_seq,
            "wal": self.shards[0].wal_json(),
            "mapEpoch": self.map_epoch,
            "shards": [sh.to_json() for sh in self.shards],
            "resyncFloors": floors,
            # Router-side progress counters (resync/catch-up/anti-entropy
            # rounds, divergence, fan-out outcomes) all live under the
            # replica.* prefix.
            "routerStats": {
                k: v for k, v in router_stats.items()
                if k.startswith("replica.")
            },
            "partial": scraped_ok < len(table),
            # Router-door fair-share state (weights, inflight, debt,
            # shed counts per tenant) — {} when tenancy is off.
            "tenants": (
                self.admission.tenants_snapshot()
                if self.admission is not None
                else {}
            ),
            "groups": groups_out,
        }
        return 200, "application/json", (json.dumps(payload) + "\n").encode(), {}

    # -- health probe + catch-up ------------------------------------------

    def _probe_once(self) -> None:
        for sh in self.shards:
            self._probe_shard(sh)

    def _probe_shard(self, sh) -> None:
        now = time.monotonic()
        with self._mu:
            # STALE groups stay in the rotation (at probe-max-interval
            # cadence, armed when they went stale): the automated
            # resync needs a live door back in, and so does an
            # operator-resynced group — PR 7 excluded them forever.
            due = [
                g for g in sh.groups
                if (not g.healthy or not g.caught_up or g.stale)
                and g.probe_at <= now
            ]
        for g in due:
            try:
                req = urllib.request.Request(g.base + "/replica/health", method="GET")
                with urllib.request.urlopen(req, timeout=2.0) as resp:
                    ok = resp.status == 200
                    hdr = resp.headers.get(GROUP_HEADER)
                    try:
                        health = json.loads(resp.read())
                    except ValueError:
                        health = {}
            except (urllib.error.URLError, OSError):
                # Unreachable OR alive-but-degraded (an HTTPError is a
                # URLError): back the probe off and try again later.
                self._backoff(g)
                continue
            if not ok:
                self._backoff(g)
                continue
            self._note_epoch(g, hdr)
            reported = health.get("appliedSeq")
            if reported is not None:
                # The probe is AUTHORITATIVE for a restarted group: a
                # fresh incarnation reports where its persisted state
                # actually stands, which may be BEHIND what the router
                # remembered of its predecessor.
                with self._mu:
                    g.applied_seq = int(reported)
                    spec.emit("probe_mark", src=id(sh.wal), group=g.name,
                              epoch=g.epoch, value=int(reported))
                self.stats.gauge(
                    f"replica.lag.{g.name}",
                    max(0, sh.wal.last_seq - int(reported)),
                )
            if g.suspect:
                # The group 4xx'd a write a sibling applied: content
                # presumed diverged until a digest check against a
                # donor clears it (resyncing on mismatch).
                if not sh.resync.verify(g):
                    self._backoff(g)
                    continue
            if sh.resync.needed(g):
                # Stale (the shard's WAL compacted past its lag), blank
                # (applied_seq=0 over a non-empty sequence space), or
                # an uncovered gap: replay alone cannot (or should not,
                # write by write) converge it — drive a fragment-level
                # RESYNC round instead of parking it for an operator.
                if not sh.resync.resync(g):
                    self._backoff(g)
                    continue
            elif reported is not None and sh.catchup.needed(g):
                if not sh.catchup.catch_up(g):
                    self._backoff(g)
                    continue
            else:
                # Legacy group (no applied-seq reporting) or already at
                # the head: nothing to replay.
                with self._mu:
                    g.caught_up = True
            self.stats.gauge(f"replica.lag.{g.name}", 0)
            self._mark_healthy(g)

    def _probe_loop(self) -> None:
        tick = min(max(self.probe_interval_s / 4.0, 0.02), 0.5)
        while not self._stop.wait(tick):
            try:
                self._probe_once()
            except Exception:  # noqa: BLE001 — the probe must never die
                self.stats.count("replica.probe_errors")

    # -- anti-entropy sweep -----------------------------------------------

    def _anti_entropy_once(self) -> None:
        """One cross-group divergence sweep: fetch every in-rotation
        group's content digest under the sequencer lock (a CONSISTENT
        CUT — no write can be sequenced between the fetches, so a
        mid-sweep write cannot masquerade as divergence), compare, and
        repair any mismatched fragment from the majority copy via the
        resync fragment stream.  Divergence is counted per group
        (``replica.divergence.<g>``) and logged as one structured
        ``pilosa_tpu.divergence`` line naming the first differing
        (index, frame, view, slice) path — a correctness event, never
        silent.  The repair work under the lock is budget-bounded
        (``anti_entropy_budget_s``); an over-budget sweep stops and the
        next sweep finishes."""
        for sh in self.shards:
            self._anti_entropy_shard(sh)

    def _anti_entropy_shard(self, sh) -> None:
        """One shard's divergence sweep: digests are only comparable
        WITHIN a shard's group set (siblings hold the same slice
        range), so the sweep runs per shard under that shard's
        sequencer."""
        ready = sh._ready_groups()
        if len(ready) < 2:
            return
        self.stats.count("replica.antientropy_rounds")
        by_name = {g.name: g for g in ready}
        with sh._seq_mu:
            digests: dict[str, dict] = {}
            for g in ready:
                try:
                    digests[g.name] = sh.resync._digest(g)
                except (OSError, ResyncAbort):
                    # A group that cannot answer is the probe's problem,
                    # not this sweep's — compare whoever answered.
                    self.stats.count("replica.antientropy_abort")
                    return
            if len({d.get("digest") for d in digests.values()}) == 1:
                return  # the common case: one string compare, no walk
            plan = majority_plan(digests)
            if not plan.divergent:
                # Digests differ only in schema (an empty index one
                # group lacks): no fragment carries different bits, so
                # nothing to repair — still worth a counter.
                self.stats.count("replica.antientropy_schema_only")
                return
            for name in sorted(plan.divergent):
                self.stats.count(f"replica.divergence.{name}")
            _divergence_logger.warning(
                "divergence %s",
                json.dumps({
                    "groups": sorted(plan.divergent),
                    "first_path": plan.first_path,
                    "paths": sum(len(p) for p in plan.divergent.values()),
                    "write_seq": sh.write_seq,
                    "shard": sh.name,
                }, separators=(",", ":")),
            )
            deadline = time.monotonic() + self.anti_entropy_budget_s
            for name in sorted(plan.divergent):
                g = by_name[name]
                for path in plan.divergent[name]:
                    if time.monotonic() > deadline:
                        self.stats.count("replica.antientropy_stall")
                        return
                    donor = by_name[plan.donor[path]]
                    try:
                        sh.resync._stream_fragment(donor, g, path, g.epoch)
                    except (OSError, ResyncAbort):
                        self.stats.count("replica.antientropy_abort")
                        return
                    self.stats.count("replica.divergence_repaired")

    def _anti_entropy_loop(self) -> None:
        base = self.anti_entropy_interval_s
        while not self._stop.wait(base * self._rng.uniform(0.75, 1.25)):
            try:
                self._anti_entropy_once()
            except Exception:  # noqa: BLE001 — the sweep must never die
                self.stats.count("replica.antientropy_errors")

    # -- live resharding ---------------------------------------------------

    def _handle_reshard(self, body: bytes):
        """``POST /replica/reshard``: split one shard live.  Body::

            {"shard": "s0", "at": 4, "name": "s1",
             "groups": ["g2=host:port", "g3=host:port"]}

        moves slices ``[at, hi)`` of ``shard`` onto the brand-new
        ``groups`` (every spec explicitly named) with zero downtime and
        zero failed writes: bulk fragments PRE-STREAM while the old
        shard keeps serving, then the routing gate drains in-flight
        requests, the (small) delta streams, the map flips behind a
        bumped ownership epoch, the moved range is cleared off the old
        owners, and the old WAL compacts to head."""
        try:
            req = json.loads(body or b"{}")
            shard_name = str(req.get("shard") or "")
            at = int(req.get("at"))
            new_name = str(req.get("name") or f"s{len(self.shards)}")
            group_specs = [str(s) for s in (req.get("groups") or [])]
        except (ValueError, TypeError):
            self.stats.count("replica.reshard.refused")
            return (
                400, "application/json",
                json.dumps({"error": "reshard body must be JSON with "
                            "shard, at (int), groups[]"}).encode(), {},
            )
        try:
            return self._reshard(shard_name, at, new_name, group_specs)
        except ShardMapError as e:
            self.stats.count("replica.reshard.refused")
            return (
                400, "application/json",
                json.dumps({"error": str(e)}).encode(), {},
            )
        except (OSError, ResyncAbort) as e:
            # Data motion failed BEFORE the flip: nothing changed
            # ownership, partial fragments on the new groups are inert
            # (and the next attempt's stream resumes them).
            self.stats.count("replica.reshard.errors")
            return (
                502, "application/json",
                json.dumps({"error": f"reshard aborted: {e}"}).encode(), {},
            )

    def _reshard_refused(self, why: str):
        self.stats.count("replica.reshard.refused")
        return (
            409, "application/json",
            json.dumps({"error": f"reshard refused: {why}"}).encode(), {},
        )

    def _reshard(self, shard_name: str, at: int, new_name: str,
                 group_specs: list):
        t0 = time.perf_counter()
        old = self._shard_by_name(shard_name)  # ShardMapError on miss
        if at <= old.lo or (old.hi is not None and at >= old.hi):
            raise ShardMapError(
                f"split point {at} outside shard {shard_name}'s range "
                f"[{old.lo}, {old.hi if old.hi is not None else ''})"
            )
        if not group_specs:
            raise ShardMapError("reshard needs at least one new group")
        for gs_ in group_specs:
            head = gs_.split("=", 1)[0]
            if "=" not in gs_ or "://" in head:
                raise ShardMapError(
                    f"reshard group spec {gs_!r} must be name=host:port "
                    "(explicit names — positional g<i> names would collide)"
                )
        # Validate the candidate map BEFORE any data motion: the split
        # shard keeps [lo, at), the new shard takes [at, hi).
        cand = []
        for s in self.shard_map:
            if s.name == shard_name:
                cand.append(Shard(s.name, s.lo, at, s.group_specs))
                cand.append(Shard(new_name, at, s.hi, group_specs))
            else:
                cand.append(Shard(s.name, s.lo, s.hi, s.group_specs))
        new_map = ShardMap(cand)
        new_groups = [_parse_group_spec(0, gs_) for gs_ in group_specs]
        if {g.name for g in new_groups} & {g.name for g in self.groups}:
            raise ShardMapError("new group names collide with existing groups")
        # Cheap preconditions before moving a byte.
        if not old.quorate():
            return self._reshard_refused(f"shard {shard_name} is not quorate")
        for g in new_groups:
            try:
                st, _ct, _p, _h = self._forward(
                    g, "GET", "/replica/health", b"", {}, timeout_s=5.0
                )
            except OSError as e:
                return self._reshard_refused(f"new group {g.name}: {e}")
            if st != 200:
                return self._reshard_refused(
                    f"new group {g.name}: HTTP {st} on health probe"
                )
        donor = old.resync._pick_donor(None)
        if donor is None:
            return self._reshard_refused(
                f"shard {shard_name} has no donor group"
            )

        def _moved(path_key: str) -> bool:
            sl = parse_fragment_path(path_key)[3]
            return sl >= at and (old.hi is None or sl < old.hi)

        new_rt = ShardRuntime(
            self, Shard(new_name, at, old.hi, group_specs), new_groups,
            self._shard_wal(new_name),
        )
        moved_fragments = 0
        moved_bytes = 0
        # PHASE 1 — pre-stream (unfenced): schema plus the bulk of the
        # moved range copies while the old shard keeps serving; writes
        # landing during the copy are in the fence delta.
        donor_digest = old.resync._digest(donor)
        pre = {
            p: c for p, c in (donor_digest.get("fragments") or {}).items()
            if _moved(p)
        }
        for g in new_groups:
            target_digest = old.resync._digest(g)
            old.resync._push_schema(donor_digest, target_digest, g, None)
            have = target_digest.get("fragments") or {}
            for p, chk in sorted(pre.items()):
                if have.get(p) == chk:
                    continue  # a resumed attempt already moved it
                # A fragment the target lacks entirely may negotiate
                # the columnar (Arrow -> /bulk) path when enabled.
                moved_bytes += old.resync._stream_fragment(
                    donor, g, p, None, laggard_empty=p not in have
                )
                moved_fragments += 1
        # PHASE 2 — the epoch fence: hold new routed requests at the
        # gate, drain the in-flight ones, stream the (small) delta,
        # flip.  No lock is held across any socket — the gate is a
        # flag; blocked requests wait on the condition, not on us.
        with self._gate_cv:
            self._gated = True
            fence_deadline = time.monotonic() + 30.0
            while self._active_routed > 0:
                if time.monotonic() > fence_deadline:
                    self._gated = False
                    self._gate_cv.notify_all()
                    return self._reshard_refused(
                        "fence drain timed out with requests in flight"
                    )
                self._gate_cv.wait(timeout=1.0)
        t_fence = time.perf_counter()
        try:
            # Delta: whatever the moved range gained (or lost) since the
            # pre-stream.  The gate guarantees no new write can land, so
            # this digest is the final pre-flip truth.
            delta_digest = old.resync._digest(donor)
            post = {
                p: c
                for p, c in (delta_digest.get("fragments") or {}).items()
                if _moved(p)
            }
            changed = [p for p, c in sorted(post.items()) if pre.get(p) != c]
            vanished = [p for p in sorted(pre) if p not in post]
            for g in new_groups:
                for p in changed + vanished:
                    moved_bytes += old.resync._stream_fragment(donor, g, p, None)
                    moved_fragments += 1
            # THE FLIP: reference-swap the map, the runtime list, and
            # the group->shard table (readers on other threads see the
            # old or the new object, never a half-built one), then bump
            # the ownership epoch.
            old.hi = at
            self.shard_map = new_map
            self.shards = sorted(self.shards + [new_rt], key=lambda r: r.lo)
            self.groups = self.groups + new_groups
            gmap = dict(self._group_shard)
            for g in new_groups:
                gmap[g] = new_rt
            self._group_shard = gmap
            self.map_epoch += 1
            spec.emit("reshard", src=id(self), epoch=self.map_epoch,
                      shard=shard_name, new=new_name, at=at)
            self.stats.gauge("replica.shard.count", len(self.shards))
            self.stats.gauge("replica.shard.map_epoch", self.map_epoch)
            for g in new_groups:
                self.stats.gauge(f"replica.healthy.{g.name}", 1)
                self.stats.gauge(f"replica.inflight.{g.name}", 0)
                self.stats.gauge(f"replica.lag.{g.name}", 0)
            # Old-WAL records for the moved range must never replay onto
            # the old groups post-clear: compact to head.  Laggard old
            # groups lose replay coverage and take the RESYNC path
            # instead — whose donor diff also streams them the clears.
            spec.emit("compact_plan", src=id(old.wal),
                      floor=old.wal.last_seq, tracked={}, floors=[])
            old.wal.compact(old.wal.last_seq)
            # Clear the moved range off the old owners (an in-rotation
            # old group still holding moved fragments would double-count
            # them under unscoped fan-out reads).  A failed clear marks
            # the group suspect — the probe's digest check repairs it —
            # and a same-server old/new pairing (dev rigs) skips the
            # clear: the "two groups" share one holder.
            clear_errors = []
            new_bases = {g.base for g in new_groups}
            for g in old.groups:
                if g.base in new_bases:
                    self.stats.count("replica.reshard.clear_skipped")
                    continue
                for p in sorted(post):
                    qs = fragment_query(p)
                    try:
                        old.resync._push(
                            g, "POST",
                            f"/fragment/import-roaring?{qs}&total=0&crc=0&off=0",
                            b"", None, ctype="application/octet-stream",
                        )
                    except (OSError, ResyncAbort) as e:
                        self.stats.count("replica.reshard.clear_errors")
                        clear_errors.append(f"{g.name}: {p}: {e}")
                        with self._mu:
                            g.suspect = True
                            g.caught_up = False
                        break
        finally:
            with self._gate_cv:
                self._gated = False
                self._gate_cv.notify_all()
        fence_ms = (time.perf_counter() - t_fence) * 1e3
        self.stats.count("replica.reshard.rounds")
        self.stats.count("replica.reshard.moved_fragments", moved_fragments)
        self.stats.count("replica.reshard.moved_bytes", moved_bytes)
        self.stats.timing("replica.reshard.fence_ms", fence_ms)
        payload = {
            "mapEpoch": self.map_epoch,
            "shards": self.shard_map.to_json(),
            "moved": {"fragments": moved_fragments, "bytes": moved_bytes},
            "fenceMs": round(fence_ms, 3),
            "totalMs": round((time.perf_counter() - t0) * 1e3, 3),
            "clearErrors": clear_errors,
        }
        return 200, "application/json", json.dumps(payload).encode(), {}

    # -- lifecycle --------------------------------------------------------

    class _Handler(BaseHTTPRequestHandler):
        router: "ReplicaRouter"
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _run(self, method: str) -> None:
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            headers = {k.lower(): v for k, v in self.headers.items()}
            status, ctype, payload, extra = self.router.handle(
                method, self.path, body, headers
            )
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            for k, v in extra.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            self._run("GET")

        def do_POST(self):
            self._run("POST")

        def do_DELETE(self):
            self._run("DELETE")

        def do_PATCH(self):
            self._run("PATCH")

    def serve(self) -> "ReplicaRouter":
        """Bind and serve in a background thread; returns self (the
        resolved port lands in ``self.port``)."""
        cls = type("BoundRouter", (self._Handler,), {"router": self})
        self._httpd = ThreadingHTTPServer((self.host, self.port), cls)
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._probe_thread = threading.Thread(target=self._probe_loop, daemon=True)
        self._probe_thread.start()
        if self.anti_entropy_interval_s > 0:
            threading.Thread(
                target=self._anti_entropy_loop, daemon=True
            ).start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for sh in self.shards:
            sh.wal.close()


def router_from_config(cfg, stats=None, tracer=None) -> ReplicaRouter:
    """Build a router from Config ([replica] TOML + PILOSA_TPU_REPLICA_*
    env, resolved by Config itself) — the CLI entry point's constructor.

    Shard map resolution (the config satellite's contract): an explicit
    ``shard-map`` string wins; else ``shards = N`` (N > 1) auto-splits
    the flat group list with ``uniform_shard_map``; else the degenerate
    single-shard map — which keeps the historical single-WAL layout
    (``<wal-dir>/router.wal``) byte-identical to the pre-shard router.
    Multi-shard routers get per-shard WALs (``router-<shard>.wal``)
    built lazily by the router itself from ``wal_dir``."""
    import os

    host, _, port = (cfg.host or "127.0.0.1").replace("http://", "").partition(":")
    faults = FaultInjector.from_env() or NOP_FAULTS

    shard_map = None
    if (cfg.replica_shard_map or "").strip():
        shard_map = parse_shard_map(cfg.replica_shard_map)
    elif int(cfg.replica_shards or 1) > 1:
        shard_map = uniform_shard_map(
            cfg.replica_groups, int(cfg.replica_shards),
            span=int(cfg.replica_shard_span or 1),
        )

    # [tenancy]: the router runs the SAME fair-share door the servers
    # do, from the same config — one [tenancy] section isolates tenants
    # at every entry point.  Disabled (the default) passes None for
    # both, which keeps handle() on the doorless fast path.
    from pilosa_tpu import tenancy as tenancy_mod

    tenancy = tenancy_mod.from_config(cfg, stats=stats)
    admission = None
    if tenancy is not None:
        admission = qos.AdmissionController(
            depths={
                qos.CLASS_READ: cfg.qos_read_depth,
                qos.CLASS_WRITE: cfg.qos_write_depth,
                qos.CLASS_ADMIN: cfg.qos_admin_depth,
            },
            queue_wait_ms=cfg.qos_queue_wait_ms,
            retry_after_ms=cfg.qos_retry_after_ms,
            stats=stats,
            tenancy=tenancy,
        )

    common = dict(
        host=host or "127.0.0.1",
        port=cfg.replica_router_port,
        failover=cfg.replica_failover,
        default_deadline_ms=cfg.default_deadline_ms,
        probe_interval_s=cfg.replica_probe_interval,
        probe_max_interval_s=cfg.replica_probe_max_interval,
        faults=faults,
        stats=stats,
        tracer=tracer,
        anti_entropy_interval_s=cfg.replica_anti_entropy_interval,
        resync_chunk_bytes=cfg.replica_resync_chunk_bytes,
        resync_columnar=cfg.replica_resync_columnar,
        admission=admission,
        tenancy=tenancy,
    )
    if shard_map is not None and len(shard_map) > 1:
        return ReplicaRouter(
            shard_map=shard_map,
            wal_dir=cfg.replica_wal_dir,
            wal_max_bytes=cfg.replica_wal_max_bytes,
            **common,
        )
    wal = WriteAheadLog(
        os.path.join(os.path.expanduser(cfg.replica_wal_dir), "router.wal")
        if cfg.replica_wal_dir
        else None,
        max_bytes=cfg.replica_wal_max_bytes,
        stats=stats if stats is not None else NOP_STATS,
        faults=faults,
    )
    if shard_map is not None:
        # A one-shard explicit map: honor its group specs but keep the
        # historical single-WAL filename.
        return ReplicaRouter(shard_map=shard_map, wal=wal, **common)
    return ReplicaRouter(cfg.replica_groups, wal=wal, **common)
