"""The replica read router: one front door over N serving groups.

The reference fans a read to ANY of a fragment's ``ReplicaN`` owners at
query time (executor.go:1147-1159) — replication buys read throughput,
not just durability.  This router is that idea at GROUP granularity:
each group is a complete serving unit (a lockstep job or a plain
server) holding a full copy of every slice, so ANY group can answer ANY
read and read QPS scales with group count.

Routing policy:

- CLASSIFY with the QoS classifier (``qos.classify_request`` — the same
  byte-scan the admission door uses, so a request is a write here iff
  it is a write there).  A false read->write positive only costs fan-out
  latency; a false negative is impossible for PQL mutating calls.
- READS (and admin GETs) go to ONE healthy group: least-inflight pick,
  ties broken by fewest-routed so an idle router round-robins.  On a
  connect failure or a 5xx answer the group is marked unhealthy and the
  read fails over ONCE to a sibling group (reads are side-effect-free,
  so the retry is safe; ``[replica] failover = false`` disables it).
- WRITES (and mutating admin — schema must stay identical everywhere)
  ship to ALL groups through ONE sequencer: the sequencer lock is held
  for the whole fan-out, so every group applies every write in the same
  total order and the groups' fragment generation vectors advance
  identically.  That is the invariant that keeps each group's qcache
  and serve-state repair read-your-writes correct with zero cross-group
  invalidation traffic.  A write is ACKed only after EVERY group
  applied it, so a read routed to any group immediately after the ack
  sees it.

Failure semantics:

- The group set must be QUORATE (every configured group healthy) for
  writes: a write against a degraded set answers 503 + Retry-After
  WITHOUT touching any group.  Because no write is accepted while a
  group is down, a recovering group missed no acknowledged writes and
  rejoins with no catch-up protocol.
- A write that fails MID-fan-out (connect error / 5xx from one group)
  answers 502: it may be partially applied (earlier groups committed).
  The failed group is marked unhealthy — so reads stop routing there
  and further writes refuse — and the client retries the (idempotent)
  write once the set is quorate again.
- A write SHED by a group (429, or any answer carrying Retry-After —
  the admission door under load) is load-dependent, not deterministic,
  so it is never ACKed as a success: shed before any group committed
  passes the backpressure through verbatim (no demotion); shed after a
  sibling committed is a partial write (502 + demotion) like a 5xx.
- A read answered 504 spent ITS OWN deadline budget — request-scoped,
  not a group-health signal — so it returns to the client without
  demoting the group (a burst of tight-deadline reads must not refuse
  writes cluster-wide via the quorum rule).
- Health recovery is probe-driven: a background thread GETs
  ``/replica/health`` on unhealthy groups and restores them on a 200.
  A restarted group comes back with a bumped epoch in its
  ``X-Pilosa-Group`` header; the router records it and counts
  ``replica.epoch_bump``.

Observability: ``replica.routed.<group>`` / ``replica.failover`` /
``replica.write_fanout`` (+ refused/error) counters and per-group
``replica.healthy.<group>`` / ``replica.inflight.<group>`` gauges at
the router's own ``/debug/vars``; routed requests tag their trace root
with ``group=<g>`` (and graft the group's span tree under the forward
span), so the router's ``/debug/traces`` shows which replica served a
read.  ``/replica/status`` returns the live group table.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from pilosa_tpu import qos
from pilosa_tpu.qos import DEADLINE_HEADER
from pilosa_tpu.replica import GROUP_HEADER
from pilosa_tpu.stats import NOP_STATS
from pilosa_tpu.trace import TRACE_HEADER, TRACE_SPANS_HEADER

# Headers never forwarded on a hop: ownership is per-connection, the
# router recomputes lengths, and deadline/trace headers are REWRITTEN
# (remaining budget, router trace id) rather than copied.
_HOP_HEADERS = frozenset(
    ("host", "content-length", "connection", "accept-encoding",
     DEADLINE_HEADER.lower(), TRACE_HEADER.lower())
)


class GroupState:
    """Router-side record of one serving group."""

    __slots__ = ("name", "base", "healthy", "inflight", "routed", "epoch")

    def __init__(self, name: str, base: str):
        self.name = name
        if "://" not in base:
            base = "http://" + base
        self.base = base.rstrip("/")
        self.healthy = True
        self.inflight = 0
        self.routed = 0
        self.epoch: Optional[str] = None  # last X-Pilosa-Group seen

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "base": self.base,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "routed": self.routed,
            "epoch": self.epoch,
        }


def _parse_group_spec(i: int, spec: str) -> GroupState:
    """``host:port`` or ``name=host:port`` (names default to g<i>)."""
    spec = spec.strip()
    if "=" in spec and "://" not in spec.split("=", 1)[0]:
        name, base = spec.split("=", 1)
        return GroupState(name.strip(), base.strip())
    return GroupState(f"g{i}", spec)


class ReplicaRouter:
    """HTTP front door fanning reads over replica serving groups."""

    def __init__(
        self,
        groups,
        host: str = "127.0.0.1",
        port: int = 0,
        failover: bool = True,
        default_deadline_ms: float = 0.0,
        timeout: float = 30.0,
        probe_interval_s: float = 1.0,
        stats=None,
        tracer=None,
    ):
        if not groups:
            raise ValueError("replica router needs at least one group")
        self.groups = [_parse_group_spec(i, g) for i, g in enumerate(groups)]
        if len({g.name for g in self.groups}) != len(self.groups):
            raise ValueError("duplicate replica group names")
        self.host = host
        self.port = port
        self.failover = failover
        self.default_deadline_ms = default_deadline_ms
        self.timeout = timeout
        self.probe_interval_s = probe_interval_s
        self.stats = stats if stats is not None else NOP_STATS
        self.tracer = tracer
        self._mu = threading.Lock()  # group table (health/inflight/epoch)
        # The write sequencer: held for a write's WHOLE fan-out, so all
        # groups see all writes in one total order.
        self._seq_mu = threading.Lock()
        self.write_seq = 0
        self._httpd = None
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        for g in self.groups:
            self.stats.gauge(f"replica.healthy.{g.name}", 1)
            self.stats.gauge(f"replica.inflight.{g.name}", 0)

    # -- group table ------------------------------------------------------

    def _pick(self, exclude=None) -> Optional[GroupState]:
        """Least-inflight healthy group (ties: fewest routed, so an idle
        router spreads sequential reads round-robin across groups)."""
        with self._mu:
            live = [
                g for g in self.groups
                if g.healthy and (exclude is None or g is not exclude)
            ]
            if not live:
                return None
            g = min(live, key=lambda g: (g.inflight, g.routed))
            g.routed += 1
            g.inflight += 1
            self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)
        self.stats.count(f"replica.routed.{g.name}")
        return g

    def _release(self, g: GroupState) -> None:
        with self._mu:
            g.inflight -= 1
            self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)

    def _mark_unhealthy(self, g: GroupState, why: str) -> None:
        with self._mu:
            if not g.healthy:
                return
            g.healthy = False
        self.stats.gauge(f"replica.healthy.{g.name}", 0)
        self.stats.count(f"replica.unhealthy.{g.name}")
        self.stats.set("replica.last_failure", f"{g.name}: {why}")

    def _mark_healthy(self, g: GroupState) -> None:
        with self._mu:
            if g.healthy:
                return
            g.healthy = True
        self.stats.gauge(f"replica.healthy.{g.name}", 1)
        self.stats.count("replica.recovered")

    def _note_epoch(self, g: GroupState, hdr: Optional[str]) -> None:
        """Track the group identity header; a changed epoch means the
        group restarted (in-memory generation vectors rebuilt) — counted
        so dashboards can correlate it with that group's cold caches."""
        if not hdr:
            return
        if g.epoch is not None and g.epoch != hdr:
            self.stats.count("replica.epoch_bump")
        g.epoch = hdr

    def healthy_count(self) -> int:
        with self._mu:
            return sum(1 for g in self.groups if g.healthy)

    def quorate(self) -> bool:
        """Writes need the FULL group set: while any group is down no
        write is accepted, which is exactly what lets a recovering group
        rejoin with no catch-up (it missed no acknowledged writes)."""
        return self.healthy_count() == len(self.groups)

    # -- the hop ----------------------------------------------------------

    def _forward(self, g: GroupState, method: str, path_qs: str, body: bytes,
                 headers: dict, deadline=None, trace_id: str = ""):
        """One HTTP exchange with a group.  Returns (status, ctype,
        payload, response headers); raises OSError on a connect/transport
        failure (the caller's failover trigger)."""
        fwd = {k: v for k, v in headers.items() if k.lower() not in _HOP_HEADERS}
        timeout = self.timeout
        if deadline is not None:
            # Hop rule (qos/deadline.py): forward the REMAINING budget,
            # tighten the socket to match (+1s for the 504 to travel).
            fwd[DEADLINE_HEADER] = deadline.header_value()
            timeout = min(timeout, deadline.remaining_ms() / 1000.0 + 1.0)
        if trace_id:
            fwd[TRACE_HEADER] = trace_id
        req = urllib.request.Request(
            g.base + path_qs, data=body if body else None, method=method
        )
        for k, v in fwd.items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                status, payload, rheaders = resp.status, resp.read(), resp.headers
        except urllib.error.HTTPError as e:
            status, payload, rheaders = e.code, e.read(), e.headers
        except urllib.error.URLError as e:
            # Normalize to OSError for the failover path (URLError wraps
            # the socket-level reason).
            raise OSError(str(e.reason))
        self._note_epoch(g, rheaders.get(GROUP_HEADER))
        return status, rheaders.get("Content-Type", "application/json"), payload, rheaders

    # -- read path --------------------------------------------------------

    def _route_read(self, method: str, path_qs: str, body: bytes, headers: dict,
                    deadline=None, trace=None):
        g = self._pick()
        if g is None:
            return self._shed(503, "no healthy replica group", retry_after=1.0)
        attempt, first, last = 0, g, g
        while True:
            last = g
            sp = trace.root.child("forward") if trace is not None else None
            try:
                out = self._forward(
                    g, method, path_qs, body, headers, deadline=deadline,
                    trace_id=(trace.id if trace is not None else ""),
                )
            except OSError as e:
                self._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, error=str(e))
                self._mark_unhealthy(g, str(e))
                out = None
            else:
                self._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, status=out[0])
                    raw = out[3].get(TRACE_SPANS_HEADER)
                    if raw:
                        try:
                            sp.graft(json.loads(raw))
                        except ValueError:
                            pass
                if out[0] < 500 or out[0] == 504:
                    # <500 is an answer; 504 is deadline-exceeded for
                    # THIS request's own budget — request-scoped, not a
                    # group-health signal, so it must never demote the
                    # group (a burst of tight-deadline reads would
                    # otherwise mark every group unhealthy and refuse
                    # all writes via the quorum rule).
                    if trace is not None:
                        trace.root.tags["group"] = g.name
                    extra = {GROUP_HEADER: out[3].get(GROUP_HEADER) or g.name}
                    ra = out[3].get("Retry-After")
                    if ra:
                        extra["Retry-After"] = ra
                    return out[0], out[1], out[2], extra
                # Other 5xx: this group cannot serve; a degraded
                # lockstep group answers 503 until its job restarts, so
                # stop routing reads there and let the probe restore it.
                self._mark_unhealthy(g, f"HTTP {out[0]} on read")
            # One-shot failover: reads are side-effect-free, so the
            # retry on a sibling is always safe.
            if not self.failover or attempt >= 1:
                break
            attempt += 1
            g = self._pick(exclude=first)
            if g is None:
                break
            self.stats.count("replica.failover")
        if out is not None:
            return out[0], out[1], out[2], {GROUP_HEADER: last.name}
        return self._shed(503, "replica group unreachable", retry_after=1.0)

    # -- write path -------------------------------------------------------

    def _route_write(self, method: str, path_qs: str, body: bytes, headers: dict,
                     deadline=None, trace=None):
        """Total-ordered fan-out: the sequencer lock is held end to end,
        so group k's generation vectors advance through exactly the same
        write sequence as group 0's — the cross-group read-your-writes
        invariant the tests pin."""
        with self._seq_mu:
            if not self.quorate():
                with self._mu:
                    down = [g.name for g in self.groups if not g.healthy]
                self.stats.count("replica.write_refused")
                if trace is not None:
                    trace.root.tags["qos"] = "write_refused"
                return self._shed(
                    503,
                    f"write refused: replica group set not quorate (down: {', '.join(down)})",
                    retry_after=1.0,
                )
            self.write_seq += 1
            first_out = None
            applied = False  # any group committed (2xx) so far
            for g in self.groups:
                sp = trace.root.child("forward") if trace is not None else None
                with self._mu:  # inflight is shared with _pick/_release
                    g.inflight += 1
                    self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)
                try:
                    out = self._forward(
                        g, method, path_qs, body, headers, deadline=deadline,
                        trace_id=(trace.id if trace is not None else ""),
                    )
                except OSError as e:
                    if sp is not None:
                        sp.finish().annotate(group=g.name, error=str(e))
                    self._mark_unhealthy(g, str(e))
                    self.stats.count("replica.write_error")
                    return self._partial_write(g, str(e))
                finally:
                    self._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, status=out[0])
                # A shed (429, or any non-5xx answer carrying
                # Retry-After) is LOAD-dependent, not deterministic:
                # under load one group can shed a write its siblings
                # applied, so it must never be ACKed as a success.
                shed = out[0] == 429 or (out[0] < 500 and out[3].get("Retry-After"))
                if shed and not applied:
                    # Shed before ANY group committed: nothing is
                    # partially applied, so pass the backpressure
                    # through verbatim — no demotion (the group is
                    # loaded, not broken) and the client just retries.
                    self.stats.count("replica.write_shed")
                    extra = {GROUP_HEADER: g.name}
                    ra = out[3].get("Retry-After")
                    if ra:
                        extra["Retry-After"] = ra
                    return out[0], out[1], out[2], extra
                if out[0] >= 500 or shed:
                    # Failed (or shed) AFTER a sibling committed: the
                    # write is partially applied.  Demote the group so
                    # further writes refuse (503) until the probe
                    # restores it — the idempotent retry then re-aligns
                    # the groups.
                    self._mark_unhealthy(g, f"HTTP {out[0]} on write")
                    self.stats.count("replica.write_error")
                    return self._partial_write(g, f"HTTP {out[0]}")
                # Deterministic 4xx (parse/schema: 400/404/409) answers
                # identically on every group (identical schema + total
                # order) — keep fanning so a mutating call that DID
                # apply elsewhere stays aligned.
                if out[0] < 300:
                    applied = True
                if first_out is None:
                    first_out = out
            self.stats.count("replica.write_fanout")
        status, ctype, payload, rheaders = first_out
        return status, ctype, payload, {GROUP_HEADER: "all"}

    def _partial_write(self, g: GroupState, why: str):
        """A write failed mid-fan-out: earlier groups committed, ``g``
        did not.  502 tells the client the write may be partially
        applied — with ``g`` now unhealthy, further writes refuse (503)
        until the probe restores the set, and the retried (idempotent)
        write re-aligns the groups."""
        return (
            502,
            "application/json",
            json.dumps({
                "error": f"write failed on group {g.name} ({why}); "
                "may be partially applied — retry when the group set is quorate"
            }).encode(),
            {"Retry-After": "1.000"},
        )

    @staticmethod
    def _shed(status: int, message: str, retry_after: float = 1.0):
        return (
            status,
            "application/json",
            json.dumps({"error": message}).encode(),
            {"Retry-After": f"{retry_after:.3f}"},
        )

    # -- dispatch ---------------------------------------------------------

    def handle(self, method: str, path_qs: str, body: bytes, headers: dict):
        """Serve one request.  Returns (status, ctype, payload, extra
        headers).  ``headers`` keys must be lowercase."""
        parsed = urlparse(path_qs)
        path = parsed.path
        if method == "GET" and path == "/debug/vars":
            snap = self.stats.snapshot() if hasattr(self.stats, "snapshot") else {}
            return 200, "application/json", (json.dumps(snap) + "\n").encode(), {}
        if method == "GET" and path == "/debug/traces":
            return self._debug_traces(parse_qs(parsed.query))
        if method == "GET" and path == "/replica/status":
            with self._mu:
                table = [g.to_json() for g in self.groups]
            payload = json.dumps({
                "groups": table,
                "quorate": all(g["healthy"] for g in table),
                "write_seq": self.write_seq,
            }).encode()
            return 200, "application/json", payload, {}

        deadline = qos.deadline_from_headers(headers, self.default_deadline_ms)
        if deadline is not None and deadline.expired():
            return (
                504, "application/json",
                json.dumps({"error": "deadline exceeded (router)"}).encode(), {},
            )
        cls = qos.classify_request(method, path, body)
        # Mutating admin (schema, deletions) must apply to EVERY group or
        # the replicas' schemas diverge; admin GETs route like reads.
        fan_all = cls == qos.CLASS_WRITE or (
            cls == qos.CLASS_ADMIN and method in ("POST", "DELETE", "PATCH")
        )
        trace = (
            self.tracer.begin(headers, name=f"{method} {path}")
            if self.tracer is not None
            else None
        )
        t0 = time.perf_counter()
        if fan_all:
            out = self._route_write(method, path_qs, body, headers,
                                    deadline=deadline, trace=trace)
        else:
            out = self._route_read(method, path_qs, body, headers,
                                   deadline=deadline, trace=trace)
        if self.tracer is not None:
            extra = self.tracer.finish_request(
                trace, name=f"{method} {path}",
                dt_ms=(time.perf_counter() - t0) * 1e3,
                body=body, status=out[0],
            )
            if extra:
                merged = dict(out[3])
                merged.update(extra)
                out = (out[0], out[1], out[2], merged)
        return out

    def _debug_traces(self, params: dict):
        if self.tracer is None:
            return 200, "application/json", b'{"traces": []}\n', {}
        try:
            min_ms = float((params.get("min-ms") or ["0"])[0] or 0)
            limit = int((params.get("limit") or ["64"])[0] or 64)
        except ValueError:
            return 400, "application/json", b'{"error": "bad min-ms/limit"}', {}
        payload = json.dumps(
            {"traces": self.tracer.traces_json(min_ms=min_ms, limit=limit)}
        ).encode()
        return 200, "application/json", payload, {}

    # -- health probe -----------------------------------------------------

    def _probe_once(self) -> None:
        with self._mu:
            down = [g for g in self.groups if not g.healthy]
        for g in down:
            try:
                req = urllib.request.Request(g.base + "/replica/health", method="GET")
                with urllib.request.urlopen(req, timeout=2.0) as resp:
                    ok = resp.status == 200
                    hdr = resp.headers.get(GROUP_HEADER)
            except (urllib.error.URLError, OSError):
                # Unreachable OR alive-but-degraded (an HTTPError is a
                # URLError): either way the group stays unhealthy.
                continue
            if ok:
                self._note_epoch(g, hdr)
                self._mark_healthy(g)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self._probe_once()
            except Exception:  # noqa: BLE001 — the probe must never die
                pass

    # -- lifecycle --------------------------------------------------------

    class _Handler(BaseHTTPRequestHandler):
        router: "ReplicaRouter"
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _run(self, method: str) -> None:
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            headers = {k.lower(): v for k, v in self.headers.items()}
            status, ctype, payload, extra = self.router.handle(
                method, self.path, body, headers
            )
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            for k, v in extra.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            self._run("GET")

        def do_POST(self):
            self._run("POST")

        def do_DELETE(self):
            self._run("DELETE")

        def do_PATCH(self):
            self._run("PATCH")

    def serve(self) -> "ReplicaRouter":
        """Bind and serve in a background thread; returns self (the
        resolved port lands in ``self.port``)."""
        cls = type("BoundRouter", (self._Handler,), {"router": self})
        self._httpd = ThreadingHTTPServer((self.host, self.port), cls)
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._probe_thread = threading.Thread(target=self._probe_loop, daemon=True)
        self._probe_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def router_from_config(cfg, stats=None, tracer=None) -> ReplicaRouter:
    """Build a router from Config ([replica] TOML + PILOSA_TPU_REPLICA_*
    env, resolved by Config itself) — the CLI entry point's constructor."""
    host, _, port = (cfg.host or "127.0.0.1").replace("http://", "").partition(":")
    return ReplicaRouter(
        cfg.replica_groups,
        host=host or "127.0.0.1",
        port=cfg.replica_router_port,
        failover=cfg.replica_failover,
        default_deadline_ms=cfg.default_deadline_ms,
        stats=stats,
        tracer=tracer,
    )
