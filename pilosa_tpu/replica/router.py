"""The replica read router: one front door over N serving groups.

The reference fans a read to ANY of a fragment's ``ReplicaN`` owners at
query time (executor.go:1147-1159) — replication buys read throughput,
not just durability.  This router is that idea at GROUP granularity:
each group is a complete serving unit (a lockstep job or a plain
server) holding a full copy of every slice, so ANY group can answer ANY
read and read QPS scales with group count.

Routing policy:

- CLASSIFY with the QoS classifier (``qos.classify_request`` — the same
  byte-scan the admission door uses, so a request is a write here iff
  it is a write there).  A false read->write positive only costs fan-out
  latency; a false negative is impossible for PQL mutating calls.
- READS (and admin GETs) go to ONE healthy, CAUGHT-UP group:
  least-inflight pick, ties broken by fewest-routed so an idle router
  round-robins.  On a connect failure or a 5xx answer the group is
  marked unhealthy and the read fails over ONCE to a sibling group
  (reads are side-effect-free, so the retry is safe; ``[replica]
  failover = false`` disables it).  A lagging group never serves reads
  — that is what preserves read-your-writes across groups now that a
  write can commit without it.
- WRITES (and mutating admin — schema must stay identical everywhere)
  run through ONE sequencer: each accepted write is assigned a
  monotonic sequence number and appended to the WRITE-AHEAD LOG
  (``replica/wal.py``) BEFORE any group sees it, then fanned to every
  in-rotation group with the sequence riding ``X-Pilosa-Write-Seq``.
  The sequencer lock is held for the whole fan-out, so every group
  applies every write in the same total order and the groups' fragment
  generation vectors advance identically — the invariant that keeps
  each group's qcache and serve-state repair read-your-writes correct
  with zero cross-group invalidation traffic.

Failure semantics (the durable-log upgrade of PR 6's full-set rule):

- QUORUM is now a MAJORITY of the configured groups.  A write COMMITS
  (2xx to the client) once >= majority of groups applied it; groups
  that are down, lagging, or failed mid-fan-out simply miss the write
  and accumulate a bounded backlog in the WAL instead of blocking the
  cluster — one dead group no longer 503s every write.  Writes refuse
  (503 + Retry-After, touching no group and appending nothing) only
  when fewer than a majority of groups are in rotation.
- A write that reached SOME group but fewer than a majority answers
  502 "may be partially applied": the record stays in the log, the
  laggards re-converge by replay, and the idempotent client retry is
  harmless.
- A write SHED by a group (429, or any answer carrying Retry-After —
  the admission door under load; one shared predicate,
  ``replica.write_not_applied``, decides "did not land" for the
  fan-out, the catch-up replay, and the group-side bookkeeping alike)
  is load-dependent, not deterministic: shed before ANY group
  committed — and with no AMBIGUOUS failure earlier in the fan-out —
  passes the backpressure through verbatim and ABORTS the log record
  (tombstoned — replay can never deliver a write no live group holds);
  shed after a sibling committed just makes the shedding group a
  laggard (demoted + replayed later), and the write still commits if a
  majority applied.
- A transport failure (or 5xx) is AMBIGUOUS: the socket may have died
  AFTER the group applied the write, so it never proves
  non-application.  Only provable refusals (shed / deterministic 4xx
  everywhere) tombstone the record; when every group failed
  ambiguously the record STAYS LIVE (502 "may be partially applied" to
  the client) and catch-up re-delivers it — idempotent re-apply is the
  contract, silent cross-group divergence is not.
- A read answered 504 spent ITS OWN deadline budget — request-scoped,
  not a group-health signal — so it returns to the client without
  demoting the group.
- RECOVERY is probe + replay: a background loop probes down/lagging
  groups with jittered exponential backoff per group (``[replica]
  probe-interval`` base, doubled per failed probe up to
  ``probe-max-interval``, reset on recovery — a dead group is not
  hammered in lockstep by every router).  A live group reporting a
  stale applied sequence gets the missed WAL suffix streamed in order
  (``replica/catchup.py``; epoch-guarded, so a restarted incarnation
  can't absorb a replay paced against its predecessor) and only
  rejoins the read rotation once FULLY caught up.  A laggard whose
  backlog would grow the WAL past ``wal-max-bytes`` is declared STALE
  (``replica.stale.<g>``): the log compacts past it, and the probe —
  which keeps visiting stale groups at ``probe-max-interval`` — drives
  an AUTOMATED RESYNC (``replica/resync.py``): digest diff against a
  healthy donor, differing fragments streamed as serialized roaring
  payloads, applied-sequence seeded under the sequencer lock, WAL
  catch-up for the final drain — no human in the loop.  A group
  reporting ``applied_seq=0`` over a non-empty sequence space (blank
  data dir) takes the same path.
- ANTI-ENTROPY: an optional background sweep (``[replica]
  anti-entropy-interval``, jittered, off by default) compares healthy
  groups' content digests under the sequencer lock and repairs any
  silently diverged fragment from the majority copy
  (``replica.divergence.<g>`` + one structured
  ``pilosa_tpu.divergence`` log line per divergent sweep).

Observability: ``replica.routed.<group>`` / ``replica.failover`` /
``replica.write_fanout`` (+ refused/error/shed), per-group
``replica.healthy.<group>`` / ``replica.inflight.<group>`` /
``replica.lag.<group>`` gauges and ``replica.wal_bytes`` at the
router's own ``/debug/vars``; ``/replica/status`` returns the live
group table (health, applied sequence, lag, caught-up/stale flags) and
the WAL head/tail.  Routed requests tag their trace root with
``group=<g>`` and graft the group's span tree under the forward span.
Deterministic fault injection (``replica/faults.py``,
``PILOSA_TPU_FAULT_SPEC``) hooks the per-group forward and the WAL
append, so partial-failure orderings are reproducible in tests.
"""

from __future__ import annotations

import json
import logging
import random
import threading

from pilosa_tpu.analysis import lockcheck
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from pilosa_tpu import metrics as metrics_mod
from pilosa_tpu import qos
from pilosa_tpu.analysis import spec
from pilosa_tpu.qos import DEADLINE_HEADER
from pilosa_tpu.replica import (
    APPLIED_SEQ_HEADER,
    GROUP_HEADER,
    REPLAY_HEADER,
    WRITE_SEQ_HEADER,
    write_not_applied,
)
from pilosa_tpu.replica.catchup import CatchupManager
from pilosa_tpu.replica.digest import majority_plan
from pilosa_tpu.replica.faults import FaultInjector, InjectedStatus, NOP_FAULTS
from pilosa_tpu.replica.resync import ResyncAbort, ResyncManager
from pilosa_tpu.replica.wal import WriteAheadLog
from pilosa_tpu.stats import NOP_STATS
from pilosa_tpu.trace import TRACE_HEADER, TRACE_SPANS_HEADER

# Structured divergence log: one line per anti-entropy sweep that found
# healthy groups disagreeing (the slowquery-logger pattern) — counted
# AND logged because divergence is a correctness event, not load noise.
_divergence_logger = logging.getLogger("pilosa_tpu.divergence")

# Headers never forwarded on a hop: ownership is per-connection, the
# router recomputes lengths, deadline/trace headers are REWRITTEN
# (remaining budget, router trace id), and the write-sequence/replay
# headers are ROUTER-OWNED (a client must not be able to spoof a
# group's applied mark).
_HOP_HEADERS = frozenset(
    ("host", "content-length", "connection", "accept-encoding",
     DEADLINE_HEADER.lower(), TRACE_HEADER.lower(),
     WRITE_SEQ_HEADER.lower(), REPLAY_HEADER.lower())
)


@lockcheck.guarded_class
class GroupState:
    """Router-side record of one serving group."""

    __slots__ = ("name", "base", "healthy", "inflight", "routed", "epoch",
                 "applied_seq", "caught_up", "stale", "suspect",
                 "probe_delay", "probe_at", "__weakref__")

    # Lockset race detector declarations: the group table is written by
    # HTTP handler threads (reads, writes), the probe thread, and the
    # catch-up/resync/anti-entropy paths concurrently — every post-init
    # write must hold the router's table lock.  (The sequencer lock
    # alone is NOT enough: reads route off this state without it.)
    _guarded_by_ = {
        "healthy": "replica.router._mu",
        "inflight": "replica.router._mu",
        "routed": "replica.router._mu",
        "epoch": "replica.router._mu",
        "applied_seq": "replica.router._mu",
        "caught_up": "replica.router._mu",
        "stale": "replica.router._mu",
        "suspect": "replica.router._mu",
        "probe_delay": "replica.router._mu",
        "probe_at": "replica.router._mu",
    }

    def __init__(self, name: str, base: str):
        self.name = name
        if "://" not in base:
            base = "http://" + base
        self.base = base.rstrip("/")
        self.healthy = True
        self.inflight = 0
        self.routed = 0
        self.epoch: Optional[str] = None  # last X-Pilosa-Group seen
        # Durable-write bookkeeping: the highest WAL sequence this group
        # is known to have applied (advanced on write acks, read
        # passively off X-Pilosa-Applied-Seq, authoritative from the
        # health probe), whether it is fully caught up to the WAL head
        # (only caught-up groups serve reads or receive new writes),
        # and whether it fell so far behind the WAL compacted past it
        # (stale: operator resync required).
        self.applied_seq = 0
        self.caught_up = True
        self.stale = False
        # Content-suspect: the group answered a write with a 4xx a
        # sibling 2xx'd — for IDENTICAL replicated state that is
        # impossible, so its content is presumed diverged (blank data
        # dir, lost index) until a digest check against a healthy donor
        # clears it (or a resync round repairs it).
        self.suspect = False
        # Probe backoff (jittered exponential, per group).
        self.probe_delay = 0.0
        self.probe_at = 0.0

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "base": self.base,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "routed": self.routed,
            "epoch": self.epoch,
            "appliedSeq": self.applied_seq,
            "caughtUp": self.caught_up,
            "stale": self.stale,
            "suspect": self.suspect,
        }


def _parse_group_spec(i: int, spec: str) -> GroupState:
    """``host:port`` or ``name=host:port`` (names default to g<i>)."""
    spec = spec.strip()
    if "=" in spec and "://" not in spec.split("=", 1)[0]:
        name, base = spec.split("=", 1)
        return GroupState(name.strip(), base.strip())
    return GroupState(f"g{i}", spec)


@lockcheck.guarded_class
class ReplicaRouter:
    """HTTP front door fanning reads over replica serving groups."""

    # The write-sequence high-water mark is part of the total order the
    # sequencer lock defines; it must never be advanced outside it.
    _guarded_by_ = {
        "write_seq": "replica.router._seq_mu",
        "_fleet_cache": "replica.router._fleet_mu",
    }

    def __init__(
        self,
        groups,
        host: str = "127.0.0.1",
        port: int = 0,
        failover: bool = True,
        default_deadline_ms: float = 0.0,
        timeout: float = 30.0,
        probe_interval_s: float = 1.0,
        probe_max_interval_s: float = 30.0,
        wal: Optional[WriteAheadLog] = None,
        faults: Optional[FaultInjector] = None,
        stats=None,
        tracer=None,
        anti_entropy_interval_s: float = 0.0,
        resync_chunk_bytes: int = 256 << 10,
    ):
        if not groups:
            raise ValueError("replica router needs at least one group")
        self.groups = [_parse_group_spec(i, g) for i, g in enumerate(groups)]
        if len({g.name for g in self.groups}) != len(self.groups):
            raise ValueError("duplicate replica group names")
        self.host = host
        self.port = port
        self.failover = failover
        self.default_deadline_ms = default_deadline_ms
        self.timeout = timeout
        self.probe_interval_s = probe_interval_s
        self.probe_max_interval_s = probe_max_interval_s
        self.stats = stats if stats is not None else NOP_STATS
        self.tracer = tracer
        self.faults = faults if faults is not None else (
            FaultInjector.from_env() or NOP_FAULTS
        )
        # The durable write log: in-memory when no path was configured
        # (same sequencing/abort/replay semantics, no crash durability).
        self.wal = wal if wal is not None else WriteAheadLog(
            None, stats=self.stats, faults=self.faults
        )
        self.catchup = CatchupManager(self, self.wal, stats=self.stats)
        self.resync = ResyncManager(
            self, self.wal, stats=self.stats, chunk_bytes=resync_chunk_bytes
        )
        # Cross-group anti-entropy sweep cadence (0 = off, the test
        # default): healthy groups' digests compared, divergence counted
        # + logged + repaired from the majority copy.
        self.anti_entropy_interval_s = anti_entropy_interval_s
        # Bound on one sweep's repair work under the sequencer lock.
        self.anti_entropy_budget_s = 30.0
        self._mu = lockcheck.named_lock("replica.router._mu")  # group table (health/inflight/epoch)
        # /debug/fleet scrape cache: the last SUCCESSFUL per-group scrape
        # keeps serving (stamped stale, with its age) while a group is
        # down, so the fleet view degrades to partial instead of losing
        # the dead group entirely.
        self._fleet_mu = lockcheck.named_lock("replica.router._fleet_mu")
        self._fleet_cache: dict[str, dict] = {}
        # Per-group compaction floors for in-flight resync rounds: the
        # handoff suffix past a round's seed sequence must stay
        # replayable until the round completes (guarded by _mu).
        self._resync_floor: dict[str, int] = {}
        # The write sequencer: held for a write's WHOLE fan-out, so all
        # groups see all writes in one total order.
        self._seq_mu = lockcheck.named_lock("replica.router._seq_mu")
        self.write_seq = self.wal.last_seq
        # A router (re)started over a NON-EMPTY log must not assume any
        # group is current: a group that was lagging when the previous
        # incarnation died (or missed the unacked tail) would otherwise
        # never be detected — _note_applied only raises the mark, and
        # the probe skips caught-up groups — and would keep serving
        # reads that miss committed writes.  So everyone starts OUT of
        # the rotation at applied_seq=0, and the first health probe
        # reads each group's persisted appliedSeq AUTHORITATIVELY,
        # replays the missed suffix, and only then readmits it.  A
        # fresh log (and the in-memory default) starts everyone caught
        # up at 0.
        if self.wal.last_seq > 0:
            for g in self.groups:
                g.caught_up = False
        self._rng = random.Random()  # probe jitter (timing only)
        self._httpd = None
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        for g in self.groups:
            self.stats.gauge(f"replica.healthy.{g.name}", 1)
            self.stats.gauge(f"replica.inflight.{g.name}", 0)
            self.stats.gauge(f"replica.lag.{g.name}", 0)
        # Protocol-trace conformance (analysis/spec.py): one event when
        # a collector is installed, a None test otherwise.  The WAL's
        # identity keys this router's sequence space in the trace.
        spec.emit("config", src=id(self.wal),
                  groups=[g.name for g in self.groups], quorum=self.quorum)

    # -- group table ------------------------------------------------------

    @property
    def quorum(self) -> int:
        """Writes commit on a MAJORITY of the configured group set."""
        return len(self.groups) // 2 + 1

    def _ready_groups(self) -> list:
        """Groups in the write rotation: reachable, fully caught up to
        the WAL head, and not stale."""
        with self._mu:
            return [
                g for g in self.groups if g.healthy and g.caught_up and not g.stale
            ]

    def _pick(self, exclude=None) -> Optional[GroupState]:
        """Least-inflight healthy CAUGHT-UP group (ties: fewest routed,
        so an idle router spreads sequential reads round-robin).  A
        lagging group is invisible to reads until catch-up finishes —
        the cross-group read-your-writes rule under degraded quorum."""
        with self._mu:
            live = [
                g for g in self.groups
                if g.healthy and g.caught_up and not g.stale
                and (exclude is None or g is not exclude)
            ]
            if not live:
                return None
            g = min(live, key=lambda g: (g.inflight, g.routed))
            g.routed += 1
            g.inflight += 1
            self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)
            # Emitted under _mu so the (group, applied) observation is
            # consistent with the pick itself.
            spec.emit("read", src=id(self.wal), group=g.name,
                      applied=g.applied_seq)
        self.stats.count(f"replica.routed.{g.name}")
        return g

    def _release(self, g: GroupState) -> None:
        with self._mu:
            g.inflight -= 1
            self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)

    def _mark_unhealthy(self, g: GroupState, why: str) -> None:
        with self._mu:
            first = g.healthy
            g.healthy = False
            # Arm the probe backoff: first retry after the base
            # interval, doubling (with jitter) on every failed probe.
            if first:
                g.probe_delay = self.probe_interval_s
                g.probe_at = time.monotonic() + g.probe_delay * self._rng.uniform(0.5, 1.0)
        if not first:
            return
        self.stats.gauge(f"replica.healthy.{g.name}", 0)
        self.stats.count(f"replica.unhealthy.{g.name}")
        self.stats.set("replica.last_failure", f"{g.name}: {why}")

    def _mark_healthy(self, g: GroupState) -> None:
        with self._mu:
            if g.healthy:
                return
            g.healthy = True
            g.probe_delay = self.probe_interval_s
        self.stats.gauge(f"replica.healthy.{g.name}", 1)
        self.stats.count("replica.recovered")

    def _mark_lagging(self, g: GroupState) -> None:
        """The group missed a sequenced write: out of the read rotation
        until catch-up replays it to the WAL head."""
        with self._mu:
            g.caught_up = False
        self.stats.gauge(
            f"replica.lag.{g.name}", max(0, self.wal.last_seq - g.applied_seq)
        )

    def _backoff(self, g: GroupState) -> None:
        """One failed probe: double the group's retry delay (jittered,
        capped) so a dead group is not hammered in lockstep."""
        with self._mu:
            g.probe_delay = min(
                self.probe_max_interval_s,
                max(self.probe_interval_s, g.probe_delay * 2.0),
            )
            g.probe_at = time.monotonic() + g.probe_delay * self._rng.uniform(0.5, 1.5)

    def _note_epoch(self, g: GroupState, hdr: Optional[str]) -> None:
        """Track the group identity header; a changed epoch means the
        group restarted (in-memory generation vectors rebuilt) — counted
        so dashboards can correlate it with that group's cold caches.
        Called from every forward path (handler threads, probe thread),
        so the epoch write takes the table lock like any other
        GroupState mutation."""
        if not hdr:
            return
        with self._mu:
            bumped = g.epoch is not None and g.epoch != hdr
            g.epoch = hdr
        if bumped:
            self.stats.count("replica.epoch_bump")

    def _note_applied(self, g: GroupState, hdr: Optional[str]) -> None:
        """Passive lag tracking: every group response reports its
        applied sequence high-water mark.  The monotonic-max update is
        a read-modify-write, so it must hold the table lock — two
        concurrent responses would otherwise drop the higher mark."""
        if not hdr:
            return
        try:
            seq = int(hdr)
        except ValueError:
            return
        with self._mu:
            g.applied_seq = max(g.applied_seq, seq)
            applied = g.applied_seq
            spec.emit("mark", src=id(self.wal), group=g.name,
                      epoch=g.epoch, value=applied)
        self.stats.gauge(
            f"replica.lag.{g.name}", max(0, self.wal.last_seq - applied)
        )

    def healthy_count(self) -> int:
        with self._mu:
            return sum(1 for g in self.groups if g.healthy)

    def quorate(self) -> bool:
        """True when writes can commit: at least a MAJORITY of the
        configured groups are in rotation (healthy + caught up + not
        stale).  Minority outages degrade durability of the margin, not
        availability — the WAL replays the missed suffix to laggards."""
        return len(self._ready_groups()) >= self.quorum

    # -- the hop ----------------------------------------------------------

    def _forward(self, g: GroupState, method: str, path_qs: str, body: bytes,
                 headers: dict, deadline=None, trace_id: str = "",
                 extra_headers: Optional[dict] = None,
                 timeout_s: Optional[float] = None):
        """One HTTP exchange with a group.  Returns (status, ctype,
        payload, response headers); raises OSError on a connect/transport
        failure (the caller's failover trigger).  ``extra_headers``
        carries router-owned headers (write sequence, replay marker);
        ``timeout_s`` tightens the socket below ``self.timeout`` (the
        locked catch-up drain's per-record bound)."""
        try:
            self.faults.hit("forward", key=g.name)
        except InjectedStatus as e:
            rh = {"Retry-After": "0.250"} if e.status in (429, 503) else {}
            return (
                e.status, "application/json",
                json.dumps({"error": str(e)}).encode(), rh,
            )
        fwd = {k: v for k, v in headers.items() if k.lower() not in _HOP_HEADERS}
        timeout = self.timeout
        if timeout_s is not None:
            timeout = min(timeout, max(timeout_s, 0.001))
        if deadline is not None:
            # Hop rule (qos/deadline.py): forward the REMAINING budget,
            # tighten the socket to match (+1s for the 504 to travel).
            fwd[DEADLINE_HEADER] = deadline.header_value()
            timeout = min(timeout, deadline.remaining_ms() / 1000.0 + 1.0)
        if trace_id:
            fwd[TRACE_HEADER] = trace_id
        if extra_headers:
            fwd.update(extra_headers)
        req = urllib.request.Request(
            g.base + path_qs, data=body if body else None, method=method
        )
        for k, v in fwd.items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                status, payload, rheaders = resp.status, resp.read(), resp.headers
        except urllib.error.HTTPError as e:
            status, payload, rheaders = e.code, e.read(), e.headers
        except urllib.error.URLError as e:
            # Normalize to OSError for the failover path (URLError wraps
            # the socket-level reason).
            raise OSError(str(e.reason))
        self._note_epoch(g, rheaders.get(GROUP_HEADER))
        self._note_applied(g, rheaders.get(APPLIED_SEQ_HEADER))
        return status, rheaders.get("Content-Type", "application/json"), payload, rheaders

    # -- read path --------------------------------------------------------

    def _route_read(self, method: str, path_qs: str, body: bytes, headers: dict,
                    deadline=None, trace=None):
        g = self._pick()
        if g is None:
            return self._shed(503, "no healthy replica group", retry_after=1.0)
        attempt, first, last = 0, g, g
        while True:
            last = g
            sp = trace.root.child("forward") if trace is not None else None
            try:
                out = self._forward(
                    g, method, path_qs, body, headers, deadline=deadline,
                    trace_id=(trace.id if trace is not None else ""),
                )
            except OSError as e:
                self._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, error=str(e))
                self._mark_unhealthy(g, str(e))
                out = None
            else:
                self._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, status=out[0])
                    raw = out[3].get(TRACE_SPANS_HEADER)
                    if raw:
                        try:
                            sp.graft(json.loads(raw))
                        except ValueError:
                            pass
                if out[0] < 500 or out[0] == 504:
                    # <500 is an answer; 504 is deadline-exceeded for
                    # THIS request's own budget — request-scoped, not a
                    # group-health signal, so it must never demote the
                    # group (a burst of tight-deadline reads would
                    # otherwise mark every group unhealthy and refuse
                    # all writes via the quorum rule).
                    if trace is not None:
                        trace.root.tags["group"] = g.name
                    extra = {GROUP_HEADER: out[3].get(GROUP_HEADER) or g.name}
                    ra = out[3].get("Retry-After")
                    if ra:
                        extra["Retry-After"] = ra
                    return out[0], out[1], out[2], extra
                # Other 5xx: this group cannot serve; a degraded
                # lockstep group answers 503 until its job restarts, so
                # stop routing reads there and let the probe restore it.
                self._mark_unhealthy(g, f"HTTP {out[0]} on read")
            # One-shot failover: reads are side-effect-free, so the
            # retry on a sibling is always safe.
            if not self.failover or attempt >= 1:
                break
            attempt += 1
            g = self._pick(exclude=first)
            if g is None:
                break
            self.stats.count("replica.failover")
        if out is not None:
            return out[0], out[1], out[2], {GROUP_HEADER: last.name}
        return self._shed(503, "replica group unreachable", retry_after=1.0)

    # -- write path -------------------------------------------------------

    def _route_write(self, method: str, path_qs: str, body: bytes, headers: dict,
                     deadline=None, trace=None):
        """Sequence into the WAL, then total-ordered fan-out: the
        sequencer lock is held end to end, so group k's generation
        vectors advance through exactly the same write sequence as
        group 0's — the cross-group read-your-writes invariant the
        tests pin.  COMMIT RULE: >= majority applied -> 2xx; some but
        fewer -> 502 (record stays, laggards replay); PROVABLY none
        (shed / deterministic 4xx everywhere, no ambiguous failure) ->
        the record is aborted and the refusal surfaces verbatim;
        applied nowhere but AMBIGUOUSLY (transport failure / 5xx — the
        write may have landed before the socket died) -> the record
        stays live and replays, 502 to the client."""
        with self._seq_mu:
            ready = self._ready_groups()
            if len(ready) < self.quorum:
                with self._mu:
                    out_names = [
                        g.name for g in self.groups
                        if not (g.healthy and g.caught_up and not g.stale)
                    ]
                self.stats.count("replica.write_refused")
                if trace is not None:
                    trace.root.tags["qos"] = "write_refused"
                return self._shed(
                    503,
                    "write refused: replica group set not quorate "
                    f"(need {self.quorum}/{len(self.groups)}, out: {', '.join(out_names)})",
                    retry_after=1.0,
                )
            # DURABILITY FIRST: the record is in the log (fsync-batched)
            # before any group sees the write — a router crash mid-fan-out
            # replays the tail instead of losing the order.
            try:
                seq = self.wal.append(
                    method, path_qs, body, headers.get("content-type", "")
                )
            except OSError as e:
                self.stats.count("replica.wal_error")
                return self._shed(503, f"write log append failed: {e}", retry_after=1.0)
            self.write_seq = seq
            # Groups outside the rotation miss this sequence: their
            # backlog grows in the WAL until catch-up (or staleness).
            for g in self.groups:
                if g not in ready:
                    self._mark_lagging(g)
            first_out = None  # first answer of any kind
            first_ok = None  # first 2xx — the committed write's answer
            deterministic_4xx = None
            det4xx_groups: list = []  # groups that answered it
            applied = 0
            # Ambiguous failure: a transport error (or 5xx) proves
            # NOTHING about application — the group may have applied
            # the write before the socket died — so once one happens
            # the record can never be tombstoned this round.
            ambiguous = False
            for g in ready:
                sp = trace.root.child("forward") if trace is not None else None
                with self._mu:  # inflight is shared with _pick/_release
                    g.inflight += 1
                    self.stats.gauge(f"replica.inflight.{g.name}", g.inflight)
                try:
                    out = self._forward(
                        g, method, path_qs, body, headers, deadline=deadline,
                        trace_id=(trace.id if trace is not None else ""),
                        extra_headers={WRITE_SEQ_HEADER: str(seq)},
                    )
                except OSError as e:
                    if sp is not None:
                        sp.finish().annotate(group=g.name, error=str(e))
                    self._mark_unhealthy(g, str(e))
                    self._mark_lagging(g)
                    self.stats.count("replica.write_error")
                    ambiguous = True
                    continue
                finally:
                    self._release(g)
                if sp is not None:
                    sp.finish().annotate(group=g.name, status=out[0])
                # ONE predicate ("did the write land?") shared with the
                # catch-up replay and the group-side bookkeeping: a
                # shed (429, or any answer carrying Retry-After) is
                # LOAD-dependent, not deterministic — under load one
                # group can shed a write its siblings applied, so it
                # must never be ACKed as a success.
                missed = write_not_applied(out[0], out[3].get("Retry-After"))
                shed = missed and out[0] < 500
                if shed and applied == 0 and not ambiguous:
                    # Shed before ANY group committed, with no
                    # ambiguous failure earlier in the fan-out: nothing
                    # is applied anywhere, so abort the log record
                    # (replay must never deliver it) and pass the
                    # backpressure through verbatim — no demotion (the
                    # group is loaded, not broken); the client retries.
                    self.wal.abort(seq)
                    self.stats.count("replica.write_shed")
                    spec.emit("ack", src=id(self.wal), seq=seq,
                              status=out[0], applied=0)
                    extra = {GROUP_HEADER: g.name}
                    ra = out[3].get("Retry-After")
                    if ra:
                        extra["Retry-After"] = ra
                    return out[0], out[1], out[2], extra
                if missed:
                    # Failed (or shed) after a sibling committed or an
                    # ambiguous failure: this group missed sequence
                    # ``seq``.  Demote it — the probe + catch-up
                    # replays the suffix and only then re-admits it —
                    # and keep fanning: with the WAL holding the
                    # record, one group's failure no longer aborts the
                    # commit.
                    self._mark_unhealthy(g, f"HTTP {out[0]} on write")
                    self._mark_lagging(g)
                    self.stats.count("replica.write_error")
                    if out[0] >= 500:
                        ambiguous = True
                    continue
                with self._mu:
                    g.applied_seq = max(g.applied_seq, seq)
                spec.emit("apply", src=id(self.wal), group=g.name, seq=seq,
                          ok=out[0] < 300)
                if out[0] < 300:
                    applied += 1
                    if first_ok is None:
                        first_ok = out
                else:
                    # Deterministic 4xx (parse/schema: 400/404/409)
                    # answers identically on every group (identical
                    # schema + total order) — keep fanning so a
                    # mutating call that DID apply elsewhere stays
                    # aligned; the group's applied mark still advances
                    # (replaying it would just re-answer the same 4xx).
                    # If a SIBLING 2xx'd this very write the premise is
                    # broken — see the suspect check below the loop.
                    if deterministic_4xx is None:
                        deterministic_4xx = out
                    det4xx_groups.append(g)
                if first_out is None:
                    first_out = out
            if applied > 0 and det4xx_groups:
                # A 4xx is only "deterministic" while every replica
                # answers it.  One group 4xx-ing a write a sibling
                # APPLIED means its content diverged (a blank data dir
                # 404s the index every sibling holds; a half-applied
                # create 409s) — silently counting it applied is
                # exactly the latent divergence this tier exists to
                # kill.  Mark it SUSPECT and pull it from rotation: the
                # probe digest-checks it against a healthy donor and
                # either clears the flag (retried creates legitimately
                # answer 409 on the groups that already applied them)
                # or drives a resync round that repairs it.
                for sg in det4xx_groups:
                    with self._mu:
                        sg.suspect = True
                        sg.caught_up = False
                    self.stats.count(f"replica.suspect.{sg.name}")
                    self._mark_unhealthy(
                        sg, f"divergent answer on write {seq}"
                    )
            if applied >= self.quorum:
                # COMMITTED: a majority holds the write; any laggard
                # re-converges from the log.
                self.stats.count("replica.write_fanout")
                status, ctype, payload, _rh = first_ok or first_out
                spec.emit("ack", src=id(self.wal), seq=seq, status=status,
                          applied=applied)
                result = (status, ctype, payload, {GROUP_HEADER: "all"})
            elif applied == 0 and deterministic_4xx is not None and not ambiguous:
                # Every in-rotation group answered the same
                # deterministic 4xx: PROVABLY applied nowhere, nothing
                # to replay — tombstone the record and surface the
                # answer.
                self.wal.abort(seq)
                status, ctype, payload, _rh = deterministic_4xx
                spec.emit("ack", src=id(self.wal), seq=seq, status=status,
                          applied=0)
                result = (status, ctype, payload, {GROUP_HEADER: "all"})
            else:
                # Reached some group but not a majority — or applied
                # nowhere WE CAN PROVE (every group transport-failed /
                # 5xx'd, or shed after one did; a socket that died
                # after the request was sent may still have delivered
                # the write).  Tombstoning here could hide a write one
                # group actually holds — replay would then never
                # deliver it to the siblings, permanent cross-group
                # divergence — so the record STAYS LIVE: every demoted
                # group gets it re-delivered by catch-up (idempotent
                # re-apply is the contract) and the client hears 502
                # "may be partially applied" (retry is harmless).
                failed_names = ", ".join(
                    g.name for g in ready if g.applied_seq < seq
                )
                spec.emit("ack", src=id(self.wal), seq=seq, status=502,
                          applied=applied)
                result = self._partial_write(failed_names or "unknown")
        self._maybe_compact()
        return result

    def _partial_write(self, failed_names: str):
        """A write reached fewer than a majority of groups: 502 tells
        the client it may be partially applied — the WAL record stays,
        the lagging groups replay it during catch-up, and the
        idempotent client retry is harmless either way."""
        return (
            502,
            "application/json",
            json.dumps({
                "error": f"write failed on group(s) {failed_names}; "
                "may be partially applied — retry when the group set is quorate"
            }).encode(),
            {"Retry-After": "1.000"},
        )

    def _shed(self, status: int, message: str, retry_after: float = 1.0):
        """A router-door refusal (non-quorate write, no healthy group,
        WAL failure).  The Retry-After hint carries DECORRELATED JITTER
        (mirroring the client-side retry budget's jitter, PR 7): a
        fixed hint makes a synchronized client herd retry in lockstep
        against a recovering cluster — the exact moment it can least
        absorb a coordinated burst.  Jitter here spreads even clients
        that obey the hint literally."""
        jittered = max(0.05, self._rng.uniform(retry_after * 0.5,
                                               retry_after * 1.5))
        return (
            status,
            "application/json",
            json.dumps({"error": message}).encode(),
            {"Retry-After": f"{jittered:.3f}"},
        )

    # -- WAL compaction / backlog bound -----------------------------------

    def _maybe_compact(self) -> None:
        """Advance the log past the min-applied watermark once it has
        grown past a quarter of its bound; a laggard that would pin it
        past the bound goes STALE (replay alone can no longer rescue it
        — the automated resync streams it fragments instead) so the
        backlog stays bounded.  In-flight resync rounds FLOOR the
        watermark at their seed sequence: the handoff suffix a stale
        group is about to adopt must stay replayable."""
        if self.wal.size_bytes <= max(self.wal.max_bytes // 4, 1 << 16):
            return
        while True:
            with self._mu:
                tracked = [g for g in self.groups if not g.stale]
                floors = list(self._resync_floor.values())
                snapshot = {g.name: g.applied_seq for g in tracked}
            if not tracked and not floors:
                spec.emit("compact_plan", src=id(self.wal),
                          floor=self.wal.last_seq, tracked={}, floors=[])
                self.wal.compact(self.wal.last_seq)
                return
            min_applied = min(
                [g.applied_seq for g in tracked] + floors
            )
            spec.emit("compact_plan", src=id(self.wal), floor=min_applied,
                      tracked=snapshot, floors=floors)
            self.wal.compact(min_applied)
            if self.wal.size_bytes <= self.wal.max_bytes:
                return
            laggards = [
                g for g in tracked
                if g.applied_seq == min_applied and g.applied_seq < self.wal.last_seq
            ]
            if not laggards:
                return  # the head itself exceeds the bound; nothing to drop
            for g in laggards:
                self.stats.count(f"replica.stale.{g.name}")
                self.stats.set(
                    "replica.last_failure",
                    f"{g.name}: lag exceeded wal-max-bytes; marked stale "
                    "(automated resync scheduled)",
                )
                self._mark_unhealthy(g, "stale: WAL compacted past its lag")
                with self._mu:
                    # Stale groups stay in the probe rotation at the MAX
                    # interval — the automated resync's (and a hand-
                    # resynced group's) live door back in; PR 7 dropped
                    # them from probing forever.
                    g.stale = True
                    g.probe_delay = self.probe_max_interval_s
                    g.probe_at = time.monotonic() + g.probe_delay * self._rng.uniform(0.5, 1.0)

    # -- dispatch ---------------------------------------------------------

    def handle(self, method: str, path_qs: str, body: bytes, headers: dict):
        """Serve one request.  Returns (status, ctype, payload, extra
        headers).  ``headers`` keys must be lowercase."""
        parsed = urlparse(path_qs)
        path = parsed.path
        if method == "GET" and path == "/debug/vars":
            snap = self.stats.snapshot() if hasattr(self.stats, "snapshot") else {}
            return 200, "application/json", (json.dumps(snap) + "\n").encode(), {}
        if method == "GET" and path == "/metrics":
            return (
                200, metrics_mod.CONTENT_TYPE,
                metrics_mod.render(self.stats).encode(), {},
            )
        if method == "GET" and path == "/debug/traces":
            return self._debug_traces(parse_qs(parsed.query))
        if method == "GET" and path == "/debug/fleet":
            return self._debug_fleet(parse_qs(parsed.query))
        if method == "GET" and path == "/replica/status":
            with self._mu:
                table = [g.to_json() for g in self.groups]
                last = self.wal.last_seq
            for t in table:
                t["lag"] = max(0, last - t["appliedSeq"])
            payload = json.dumps({
                "groups": table,
                "quorate": self.quorate(),
                "quorum": self.quorum,
                "write_seq": self.write_seq,
                "wal": {
                    "firstSeq": self.wal.first_seq,
                    "lastSeq": last,
                    "bytes": self.wal.size_bytes,
                    "durable": self.wal.path is not None,
                },
            }).encode()
            return 200, "application/json", payload, {}

        deadline = qos.deadline_from_headers(headers, self.default_deadline_ms)
        if deadline is not None and deadline.expired():
            return (
                504, "application/json",
                json.dumps({"error": "deadline exceeded (router)"}).encode(), {},
            )
        cls = qos.classify_request(method, path, body)
        # Mutating admin (schema, deletions) must apply to EVERY group or
        # the replicas' schemas diverge; admin GETs route like reads.
        fan_all = cls == qos.CLASS_WRITE or (
            cls == qos.CLASS_ADMIN and method in ("POST", "DELETE", "PATCH")
        )
        trace = (
            self.tracer.begin(headers, name=f"{method} {path}")
            if self.tracer is not None
            else None
        )
        t0 = time.perf_counter()
        if fan_all:
            out = self._route_write(method, path_qs, body, headers,
                                    deadline=deadline, trace=trace)
        else:
            out = self._route_read(method, path_qs, body, headers,
                                   deadline=deadline, trace=trace)
        if self.tracer is not None:
            extra = self.tracer.finish_request(
                trace, name=f"{method} {path}",
                dt_ms=(time.perf_counter() - t0) * 1e3,
                body=body, status=out[0],
            )
            if extra:
                merged = dict(out[3])
                merged.update(extra)
                out = (out[0], out[1], out[2], merged)
        return out

    def _debug_traces(self, params: dict):
        if self.tracer is None:
            return 200, "application/json", b'{"traces": []}\n', {}
        # Malformed/out-of-range filters clamp to defaults — a debug
        # endpoint must answer, not 400 (same contract as the handler).
        min_ms = metrics_mod.clamp_float((params.get("min-ms") or [None])[0], 0.0)
        limit = metrics_mod.clamp_int((params.get("limit") or [None])[0], 64)
        payload = json.dumps(
            {"traces": self.tracer.traces_json(min_ms=min_ms, limit=limit)}
        ).encode()
        return 200, "application/json", payload, {}

    # -- /debug/fleet: the cluster-wide observability view ----------------

    def _scrape_group(self, base: str, timeout_s: float):
        """One group scrape: /replica/health (authoritative liveness +
        applied sequence) and /debug/vars (the group's own stats
        snapshot).  Returns (scrape dict, None) on success or
        (None, error string) when the health probe fails; a vars
        failure degrades to health-only rather than failing the
        scrape."""
        out: dict = {}
        try:
            req = urllib.request.Request(base + "/replica/health", method="GET")
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                out["health"] = json.loads(resp.read() or b"{}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            return None, f"health: {e}"
        try:
            req = urllib.request.Request(base + "/debug/vars", method="GET")
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                vars_snap = json.loads(resp.read() or b"{}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            vars_snap = {}
            out["varsError"] = str(e)
        out["appliedSeq"] = out["health"].get("appliedSeq")
        # Latency percentiles ride the group's qos.latency_ms.<class>
        # histograms; the rest of the snapshot is served verbatim.
        out["latencyMs"] = {
            key.split("qos.latency_ms.", 1)[1]: val
            for key, val in vars_snap.items()
            if key.startswith("qos.latency_ms.") and isinstance(val, dict)
        }
        out["vars"] = vars_snap
        return out, None

    def _debug_fleet(self, params: dict):
        """Aggregate every group's stats/health/applied-seq plus the
        router's own WAL + resync/anti-entropy progress into one
        cluster-wide JSON view.  A down group yields a PARTIAL entry:
        the router-side table row, the error, and the last successful
        scrape (if any) stamped with its age."""
        timeout_s = metrics_mod.clamp_float(
            (params.get("timeout-ms") or [None])[0], 750.0, lo=50.0, hi=10_000.0
        ) / 1e3
        now = time.time()
        with self._mu:
            table = {g.name: g.to_json() for g in self.groups}
            floors = dict(self._resync_floor)
        last = self.wal.last_seq
        groups_out = []
        scraped_ok = 0
        for name, row in table.items():
            entry = dict(row)
            # Per-group WAL depth: committed records this group has not
            # applied yet (what catch-up will replay to it).
            entry["walDepth"] = max(0, last - entry["appliedSeq"])
            scrape, err = self._scrape_group(entry["base"], timeout_s)
            if scrape is not None:
                scrape["scrapedAt"] = round(now, 3)
                with self._fleet_mu:
                    self._fleet_cache[name] = scrape
                scraped_ok += 1
            else:
                entry["error"] = err
                with self._fleet_mu:
                    scrape = self._fleet_cache.get(name)
            if scrape is not None:
                entry["scrape"] = scrape
                entry["scrapedAt"] = scrape["scrapedAt"]
                entry["ageMs"] = round(max(0.0, (now - scrape["scrapedAt"]) * 1e3), 1)
            else:
                entry["scrape"] = None
                entry["scrapedAt"] = None
                entry["ageMs"] = None
            entry["staleScrape"] = "error" in entry
            groups_out.append(entry)
        router_stats = (
            self.stats.snapshot() if hasattr(self.stats, "snapshot") else {}
        )
        payload = {
            "ts": round(now, 3),
            "quorum": self.quorum,
            "quorate": self.quorate(),
            "writeSeq": self.write_seq,
            "wal": {
                "firstSeq": self.wal.first_seq,
                "lastSeq": last,
                "bytes": self.wal.size_bytes,
                "durable": self.wal.path is not None,
            },
            "resyncFloors": floors,
            # Router-side progress counters (resync/catch-up/anti-entropy
            # rounds, divergence, fan-out outcomes) all live under the
            # replica.* prefix.
            "routerStats": {
                k: v for k, v in router_stats.items()
                if k.startswith("replica.")
            },
            "partial": scraped_ok < len(table),
            "groups": groups_out,
        }
        return 200, "application/json", (json.dumps(payload) + "\n").encode(), {}

    # -- health probe + catch-up ------------------------------------------

    def _probe_once(self) -> None:
        now = time.monotonic()
        with self._mu:
            # STALE groups stay in the rotation (at probe-max-interval
            # cadence, armed when they went stale): the automated
            # resync needs a live door back in, and so does an
            # operator-resynced group — PR 7 excluded them forever.
            due = [
                g for g in self.groups
                if (not g.healthy or not g.caught_up or g.stale)
                and g.probe_at <= now
            ]
        for g in due:
            try:
                req = urllib.request.Request(g.base + "/replica/health", method="GET")
                with urllib.request.urlopen(req, timeout=2.0) as resp:
                    ok = resp.status == 200
                    hdr = resp.headers.get(GROUP_HEADER)
                    try:
                        health = json.loads(resp.read())
                    except ValueError:
                        health = {}
            except (urllib.error.URLError, OSError):
                # Unreachable OR alive-but-degraded (an HTTPError is a
                # URLError): back the probe off and try again later.
                self._backoff(g)
                continue
            if not ok:
                self._backoff(g)
                continue
            self._note_epoch(g, hdr)
            reported = health.get("appliedSeq")
            if reported is not None:
                # The probe is AUTHORITATIVE for a restarted group: a
                # fresh incarnation reports where its persisted state
                # actually stands, which may be BEHIND what the router
                # remembered of its predecessor.
                with self._mu:
                    g.applied_seq = int(reported)
                    spec.emit("probe_mark", src=id(self.wal), group=g.name,
                              epoch=g.epoch, value=int(reported))
                self.stats.gauge(
                    f"replica.lag.{g.name}",
                    max(0, self.wal.last_seq - int(reported)),
                )
            if g.suspect:
                # The group 4xx'd a write a sibling applied: content
                # presumed diverged until a digest check against a
                # donor clears it (resyncing on mismatch).
                if not self.resync.verify(g):
                    self._backoff(g)
                    continue
            if self.resync.needed(g):
                # Stale (the WAL compacted past its lag), blank
                # (applied_seq=0 over a non-empty sequence space), or
                # an uncovered gap: replay alone cannot (or should not,
                # write by write) converge it — drive a fragment-level
                # RESYNC round instead of parking it for an operator.
                if not self.resync.resync(g):
                    self._backoff(g)
                    continue
            elif reported is not None and self.catchup.needed(g):
                if not self.catchup.catch_up(g):
                    self._backoff(g)
                    continue
            else:
                # Legacy group (no applied-seq reporting) or already at
                # the head: nothing to replay.
                with self._mu:
                    g.caught_up = True
            self.stats.gauge(f"replica.lag.{g.name}", 0)
            self._mark_healthy(g)

    def _probe_loop(self) -> None:
        tick = min(max(self.probe_interval_s / 4.0, 0.02), 0.5)
        while not self._stop.wait(tick):
            try:
                self._probe_once()
            except Exception:  # noqa: BLE001 — the probe must never die
                self.stats.count("replica.probe_errors")

    # -- anti-entropy sweep -----------------------------------------------

    def _anti_entropy_once(self) -> None:
        """One cross-group divergence sweep: fetch every in-rotation
        group's content digest under the sequencer lock (a CONSISTENT
        CUT — no write can be sequenced between the fetches, so a
        mid-sweep write cannot masquerade as divergence), compare, and
        repair any mismatched fragment from the majority copy via the
        resync fragment stream.  Divergence is counted per group
        (``replica.divergence.<g>``) and logged as one structured
        ``pilosa_tpu.divergence`` line naming the first differing
        (index, frame, view, slice) path — a correctness event, never
        silent.  The repair work under the lock is budget-bounded
        (``anti_entropy_budget_s``); an over-budget sweep stops and the
        next sweep finishes."""
        ready = self._ready_groups()
        if len(ready) < 2:
            return
        self.stats.count("replica.antientropy_rounds")
        by_name = {g.name: g for g in ready}
        with self._seq_mu:
            digests: dict[str, dict] = {}
            for g in ready:
                try:
                    digests[g.name] = self.resync._digest(g)
                except (OSError, ResyncAbort):
                    # A group that cannot answer is the probe's problem,
                    # not this sweep's — compare whoever answered.
                    self.stats.count("replica.antientropy_abort")
                    return
            if len({d.get("digest") for d in digests.values()}) == 1:
                return  # the common case: one string compare, no walk
            plan = majority_plan(digests)
            if not plan.divergent:
                # Digests differ only in schema (an empty index one
                # group lacks): no fragment carries different bits, so
                # nothing to repair — still worth a counter.
                self.stats.count("replica.antientropy_schema_only")
                return
            for name in sorted(plan.divergent):
                self.stats.count(f"replica.divergence.{name}")
            _divergence_logger.warning(
                "divergence %s",
                json.dumps({
                    "groups": sorted(plan.divergent),
                    "first_path": plan.first_path,
                    "paths": sum(len(p) for p in plan.divergent.values()),
                    "write_seq": self.write_seq,
                }, separators=(",", ":")),
            )
            deadline = time.monotonic() + self.anti_entropy_budget_s
            for name in sorted(plan.divergent):
                g = by_name[name]
                for path in plan.divergent[name]:
                    if time.monotonic() > deadline:
                        self.stats.count("replica.antientropy_stall")
                        return
                    donor = by_name[plan.donor[path]]
                    try:
                        self.resync._stream_fragment(donor, g, path, g.epoch)
                    except (OSError, ResyncAbort):
                        self.stats.count("replica.antientropy_abort")
                        return
                    self.stats.count("replica.divergence_repaired")

    def _anti_entropy_loop(self) -> None:
        base = self.anti_entropy_interval_s
        while not self._stop.wait(base * self._rng.uniform(0.75, 1.25)):
            try:
                self._anti_entropy_once()
            except Exception:  # noqa: BLE001 — the sweep must never die
                self.stats.count("replica.antientropy_errors")

    # -- lifecycle --------------------------------------------------------

    class _Handler(BaseHTTPRequestHandler):
        router: "ReplicaRouter"
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _run(self, method: str) -> None:
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            headers = {k.lower(): v for k, v in self.headers.items()}
            status, ctype, payload, extra = self.router.handle(
                method, self.path, body, headers
            )
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            for k, v in extra.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            self._run("GET")

        def do_POST(self):
            self._run("POST")

        def do_DELETE(self):
            self._run("DELETE")

        def do_PATCH(self):
            self._run("PATCH")

    def serve(self) -> "ReplicaRouter":
        """Bind and serve in a background thread; returns self (the
        resolved port lands in ``self.port``)."""
        cls = type("BoundRouter", (self._Handler,), {"router": self})
        self._httpd = ThreadingHTTPServer((self.host, self.port), cls)
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._probe_thread = threading.Thread(target=self._probe_loop, daemon=True)
        self._probe_thread.start()
        if self.anti_entropy_interval_s > 0:
            threading.Thread(
                target=self._anti_entropy_loop, daemon=True
            ).start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.wal.close()


def router_from_config(cfg, stats=None, tracer=None) -> ReplicaRouter:
    """Build a router from Config ([replica] TOML + PILOSA_TPU_REPLICA_*
    env, resolved by Config itself) — the CLI entry point's constructor."""
    import os

    host, _, port = (cfg.host or "127.0.0.1").replace("http://", "").partition(":")
    faults = FaultInjector.from_env() or NOP_FAULTS
    wal = WriteAheadLog(
        os.path.join(os.path.expanduser(cfg.replica_wal_dir), "router.wal")
        if cfg.replica_wal_dir
        else None,
        max_bytes=cfg.replica_wal_max_bytes,
        stats=stats if stats is not None else NOP_STATS,
        faults=faults,
    )
    return ReplicaRouter(
        cfg.replica_groups,
        host=host or "127.0.0.1",
        port=cfg.replica_router_port,
        failover=cfg.replica_failover,
        default_deadline_ms=cfg.default_deadline_ms,
        probe_interval_s=cfg.replica_probe_interval,
        probe_max_interval_s=cfg.replica_probe_max_interval,
        wal=wal,
        faults=faults,
        stats=stats,
        tracer=tracer,
        anti_entropy_interval_s=cfg.replica_anti_entropy_interval,
        resync_chunk_bytes=cfg.replica_resync_chunk_bytes,
    )
