"""The replica shard map: contiguous slice ranges x replica sets.

PR 6/9's replica tier scales READ QPS — every group holds a full copy
of every slice, so capacity and write throughput stay flat as groups
are added.  The shard map is the 2-D upgrade: the slice space is
partitioned into contiguous ranges (shards), each shard owns its own
replica set and its own write sequence space, and the router fans a
query out by SLICE COVER exactly like the executor's cluster fan-out
(``cluster.slices_by_node``) — each slice belongs to exactly one
owner, the union over owners is exactly the query's slice set, and a
query touching K shards costs K forwards.

Why contiguous ranges rather than the executor's hash ring: the
router's unit of REBALANCING is a range split (stream the upper half's
fragments, flip ownership behind an epoch fence — ``/replica/reshard``),
and a contiguous range moves as one fragment interval instead of a
scatter of ring partitions.  The COVER semantics are identical either
way (exact, minimal, one owner per slice); the property tests pin the
agreement against ``cluster.slices_by_node``.

Map shapes:

- **single shard** (the default, and exactly PR 6-16's behavior): one
  shard named ``s0`` covering ``[0, inf)`` with every group.
- **uniform auto-split** (``[replica] shards = N`` +
  ``shard-span = W``): N shards, shard i covering
  ``[i*W, (i+1)*W)`` (the last open-ended), the flat group list split
  into N consecutive chunks.
- **explicit map** (``[replica] shard-map``)::

      s0=0-3:g0=h:p,g1=h:p;s1=4-:g2=h:p,g3=h:p

  ``;`` separates shards, each ``name=lo-hi:groups`` with ``hi``
  omitted for open-ended and groups comma-separated (each group spec
  is the router's usual ``name=host:port`` / ``host:port``).

Validation (the config satellite's contract): ranges sorted, first at
slice 0, contiguous with no gaps or overlaps, exactly one open-ended
tail — every slice covered exactly once — and every shard holding at
least one group, with shard and group names unique across the map.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import Optional

#: Default width (in slices) of each shard under uniform auto-split.
DEFAULT_SHARD_SPAN = 256


class ShardMapError(ValueError):
    """An invalid shard map (gap, overlap, empty shard, bad spec)."""


class Shard:
    """One shard: a contiguous slice range and its replica set."""

    __slots__ = ("name", "lo", "hi", "group_specs")

    def __init__(self, name: str, lo: int, hi: Optional[int],
                 group_specs: list):
        self.name = name
        self.lo = lo
        self.hi = hi  # exclusive; None = open-ended
        self.group_specs = list(group_specs)

    def owns(self, slice_i: int) -> bool:
        return slice_i >= self.lo and (self.hi is None or slice_i < self.hi)

    def range_json(self) -> dict:
        return {"lo": self.lo, "hi": self.hi}

    def __repr__(self) -> str:
        hi = "" if self.hi is None else self.hi
        return f"Shard({self.name}, [{self.lo},{hi}), {self.group_specs})"


def _parse_group_name(i: int, spec: str) -> str:
    spec = spec.strip()
    if "=" in spec and "://" not in spec.split("=", 1)[0]:
        return spec.split("=", 1)[0].strip()
    return f"g{i}"


class ShardMap:
    """The validated shard table: every slice covered exactly once."""

    def __init__(self, shards: list):
        if not shards:
            raise ShardMapError("shard map needs at least one shard")
        shards = sorted(shards, key=lambda s: s.lo)
        names = [s.name for s in shards]
        if len(set(names)) != len(names):
            raise ShardMapError(f"duplicate shard names in {names}")
        if shards[0].lo != 0:
            raise ShardMapError(
                f"shard map must start at slice 0 (first shard "
                f"{shards[0].name} starts at {shards[0].lo})"
            )
        for a, b in zip(shards, shards[1:]):
            if a.hi is None:
                raise ShardMapError(
                    f"open-ended shard {a.name} is not last in the map"
                )
            if a.hi != b.lo:
                kind = "gap" if a.hi < b.lo else "overlap"
                raise ShardMapError(
                    f"{kind} between shard {a.name} [{a.lo},{a.hi}) and "
                    f"{b.name} starting at {b.lo} — every slice must be "
                    "covered exactly once"
                )
        if shards[-1].hi is not None:
            raise ShardMapError(
                f"last shard {shards[-1].name} must be open-ended "
                "(hi omitted) so every slice has an owner"
            )
        gnames: list[str] = []
        for s in shards:
            if not s.group_specs:
                raise ShardMapError(f"shard {s.name} has no groups")
            for spec in s.group_specs:
                gnames.append(_parse_group_name(len(gnames), spec))
        if len(set(gnames)) != len(gnames):
            raise ShardMapError(f"duplicate group names in shard map: {gnames}")
        self.shards = shards
        self._los = [s.lo for s in shards]

    def __len__(self) -> int:
        return len(self.shards)

    def __iter__(self):
        return iter(self.shards)

    def shard_of(self, slice_i: int) -> Shard:
        """The unique owner of ``slice_i`` (bisect over range starts)."""
        if slice_i < 0:
            raise ShardMapError(f"negative slice {slice_i}")
        return self.shards[bisect_right(self._los, slice_i) - 1]

    def cover(self, slices) -> dict:
        """Group a query's slice list by owning shard — the router
        analog of ``cluster.slices_by_node``: exact (the union over
        shards is exactly the input set) and minimal (only shards
        owning at least one requested slice appear; each slice appears
        exactly once, under its one owner)."""
        out: dict[str, list[int]] = {}
        for s in sorted(set(slices)):
            out.setdefault(self.shard_of(s).name, []).append(s)
        return out

    def to_json(self) -> list:
        return [
            {
                "name": s.name,
                "slices": s.range_json(),
                "groups": [
                    _parse_group_name(i, spec)
                    for i, spec in enumerate(s.group_specs)
                ],
            }
            for s in self.shards
        ]


def single_shard_map(group_specs) -> ShardMap:
    """The degenerate (and default) map: one shard, every slice, every
    group — exactly the pre-shard router."""
    return ShardMap([Shard("s0", 0, None, list(group_specs))])


def uniform_shard_map(group_specs, n_shards: int,
                      span: int = DEFAULT_SHARD_SPAN) -> ShardMap:
    """``[replica] shards = N``: split the flat group list into N
    consecutive chunks, shard i covering ``[i*span, (i+1)*span)`` with
    the last shard open-ended.  The group count must divide evenly —
    an uneven split silently giving one shard a thinner quorum is a
    config mistake, not a layout choice."""
    groups = list(group_specs)
    if n_shards < 1:
        raise ShardMapError(f"shards must be >= 1 (got {n_shards})")
    if span < 1:
        raise ShardMapError(f"shard-span must be >= 1 (got {span})")
    if not groups or len(groups) % n_shards != 0:
        raise ShardMapError(
            f"cannot split {len(groups)} group(s) evenly across "
            f"{n_shards} shard(s)"
        )
    per = len(groups) // n_shards
    shards = []
    for i in range(n_shards):
        hi = None if i == n_shards - 1 else (i + 1) * span
        shards.append(
            Shard(f"s{i}", i * span, hi, groups[i * per:(i + 1) * per])
        )
    return ShardMap(shards)


def parse_shard_map(spec: str) -> ShardMap:
    """Parse the explicit ``shard-map`` string (see module docstring).
    Raises :class:`ShardMapError` with the offending fragment."""
    shards = []
    for i, part in enumerate(p for p in spec.split(";") if p.strip()):
        part = part.strip()
        head, _, groups_s = part.partition(":")
        name = f"s{i}"
        if "=" in head:
            name, _, head = head.partition("=")
            name = name.strip()
        head = head.strip()
        lo_s, dash, hi_s = head.partition("-")
        if not dash:
            raise ShardMapError(
                f"shard {name!r}: range {head!r} must be lo-hi or lo- "
                "(hi omitted for open-ended)"
            )
        try:
            lo = int(lo_s)
            hi = int(hi_s) if hi_s.strip() else None
        except ValueError:
            raise ShardMapError(f"shard {name!r}: bad range {head!r}")
        group_specs = [g.strip() for g in groups_s.split(",") if g.strip()]
        shards.append(Shard(name, lo, hi, group_specs))
    return ShardMap(shards)
