"""Router write-ahead log: the durable total order of accepted writes.

PR 6's sequencer gave every group the same write ORDER but kept no
record of it — which is why its quorum rule had to be the full group
set (a write a down group missed could never be re-delivered).  This
log is the missing record: every write the router accepts is assigned
a monotonic sequence number and appended HERE, fsync-batched, BEFORE
any group sees it.  The log is then the recovery story end to end:

- a write commits on a DEGRADED quorum (majority of groups) because
  the laggards' missed suffix is replayable from the log;
- a crashed/restarted group re-converges by replaying the suffix past
  its last-applied sequence (``replica/catchup.py``);
- a crashed ROUTER recovers its sequence space by re-opening the log
  (the tail that never reached a quorum replays to everyone — writes
  are at-least-once, the same contract the 502 "may be partially
  applied" answer always had).

On-disk format (little-endian), one frame per record::

    [u32 payload_len][u32 crc32(payload)][payload]
    payload = [u64 seq][u32 meta_len][meta JSON][body bytes]

``meta`` carries ``{"m": method, "p": path_qs, "t": content-type}`` —
everything needed to re-forward the write verbatim — or ``{"x": true}``
for an ABORT tombstone: a write that PROVABLY applied nowhere (shed or
deterministically refused before any group committed — a transport
failure proves nothing and never tombstones) is tombstoned so replay
never delivers a write no live group has.  Recovery scans the file frame by frame; the first short or
checksum-failing frame is a torn tail from a crash mid-append — the
file is truncated there (``wal.torn_tail`` counted) and appends
continue from the last good record.

FSYNC BATCHING: appenders write+flush under the lock, then join a
group commit — one leader fsyncs for every append that landed before
the syscall, so concurrent writes share one disk flush (the classic
group-commit discipline; ``fsync=False`` trades crash durability for
speed on dev rigs).

COMPACTION: ``compact(min_applied)`` rewrites the log without records
every tracked group has applied (and without tombstones at or below the
watermark), atomically (temp file + rename).  The router calls it as
the min-applied watermark advances; a laggard pinning the log past
``max_bytes`` is the router's signal to declare that group stale
rather than grow the log without bound.
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading

from pilosa_tpu.analysis import lockcheck
import zlib
from typing import NamedTuple, Optional

from pilosa_tpu.analysis import spec
from pilosa_tpu.replica.faults import NOP_FAULTS
from pilosa_tpu.stats import NOP_STATS

_FRAME = struct.Struct("<II")  # payload_len, crc32(payload)
_HEAD = struct.Struct("<QI")  # seq, meta_len


class WalRecord(NamedTuple):
    seq: int
    method: str
    path: str
    body: bytes
    ctype: str


def _encode(seq: int, meta: dict, body: bytes) -> bytes:
    mb = json.dumps(meta, separators=(",", ":")).encode()
    payload = _HEAD.pack(seq, len(mb)) + mb + body
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def _decode_payload(payload: bytes) -> tuple[int, dict, bytes]:
    seq, meta_len = _HEAD.unpack_from(payload)
    meta = json.loads(payload[_HEAD.size : _HEAD.size + meta_len])
    return seq, meta, payload[_HEAD.size + meta_len :]


@lockcheck.guarded_class
class WriteAheadLog:
    """Append-only, checksummed, compactable write log.

    ``path=None`` keeps the log IN MEMORY: the same sequence space,
    abort, and replay semantics with no crash durability — the default
    for routers configured without ``[replica] wal-dir`` (and the unit
    the tests exercise without touching disk).
    """

    # Lockset race detector declarations: the record index and the file
    # handle move under ``_mu`` (appenders, abort, compaction swap,
    # close); the group-commit frontier state moves under the
    # ``_sync_cv`` condition's lock (leader election, generation bumps).
    # The compaction/fsync interplay here is exactly where the PR 7/8
    # reviews found hand-caught races — now machine-checked.
    _guarded_by_ = {
        "last_seq": "replica.wal._mu",
        "_offsets": "replica.wal._mu",
        "_aborted": "replica.wal._mu",
        "_mem_frames": "replica.wal._mu",
        "_end_off": "replica.wal._mu",
        "_f": "replica.wal._mu",
        "_synced_off": "replica.wal._sync_cv",
        "_syncing": "replica.wal._sync_cv",
        "_file_gen": "replica.wal._sync_cv",
    }

    def __init__(self, path: Optional[str] = None, fsync: bool = True,
                 max_bytes: int = 64 << 20, stats=None, faults=None):
        self.path = path
        self.fsync = fsync
        self.max_bytes = max_bytes
        self.stats = stats if stats is not None else NOP_STATS
        self.faults = faults if faults is not None else NOP_FAULTS
        self._mu = lockcheck.named_lock("replica.wal._mu")
        # seq -> (offset, frame_len) for live records; aborted seqs kept
        # separately so replay can skip them in O(1).
        self._offsets: dict[int, tuple[int, int]] = {}
        self._aborted: set[int] = set()
        self.last_seq = 0
        self._f: Optional[io.BufferedRandom] = None
        self._mem_frames: dict[int, bytes] = {}  # offset -> frame (path=None)
        self._end_off = 0
        # Group commit: _synced_off trails _end_off; one leader fsyncs
        # for every append that landed before its syscall.  _file_gen
        # counts file swaps (compaction/close): offsets from different
        # generations are not comparable, so the leader pins the
        # generation with the fd and a swap invalidates both.
        self._sync_cv = lockcheck.named_condition("replica.wal._sync_cv")
        self._synced_off = 0
        self._syncing = False
        self._file_gen = 0
        # Serializes whole compactions (the bulk copy runs outside _mu,
        # so two concurrent compact() calls would race on the tmp file).
        self._compact_mu = lockcheck.named_lock("replica.wal._compact_mu")
        if path is not None:
            self._open_and_recover(path)
        self.stats.gauge("replica.wal_bytes", self.size_bytes)

    # -- recovery ---------------------------------------------------------

    def _open_and_recover(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # a+b creates; reopen r+b for positioned reads AND appends.
        with open(path, "ab"):
            pass
        self._f = open(path, "r+b")
        off = 0
        data_end = os.fstat(self._f.fileno()).st_size
        while True:
            head = self._read_at(off, _FRAME.size)
            if len(head) < _FRAME.size:
                break  # clean EOF or torn length header
            n, crc = _FRAME.unpack(head)
            payload = self._read_at(off + _FRAME.size, n)
            if len(payload) < n or zlib.crc32(payload) != crc:
                break  # torn tail: crash mid-append
            seq, meta, _ = _decode_payload(payload)
            if meta.get("x"):
                self._aborted.add(seq)
                self._offsets.pop(seq, None)
            else:
                self._offsets[seq] = (off, _FRAME.size + n)
            self.last_seq = max(self.last_seq, seq)
            off += _FRAME.size + n
        if off < data_end:
            # Truncate the torn tail so the next append starts on a
            # frame boundary (re-appending over garbage would corrupt
            # the NEXT recovery scan).
            self._f.truncate(off)
            self.stats.count("wal.torn_tail")
        self._end_off = off
        self._synced_off = off

    def _read_at(self, off: int, n: int) -> bytes:
        self._f.seek(off)
        return self._f.read(n)

    # -- append / abort ---------------------------------------------------

    def append(self, method: str, path_qs: str, body: bytes, ctype: str = "") -> int:
        """Assign the next sequence number and make the record durable.
        Returns the sequence number; raises OSError on a failed append
        (the caller must refuse the write — nothing was sequenced)."""
        with self._mu:
            self.faults.hit("wal.append")
            if self.path is not None and self._f is None:
                # A file-backed log that was close()d must REFUSE, not
                # silently buffer to memory: an append that returns a
                # sequence promises a durable, replayable record (the
                # interleaving explorer's append-vs-close scenario
                # found the old fall-through losing the record).
                raise OSError("write log is closed")
            seq = self.last_seq + 1
            frame = _encode(seq, {"m": method, "p": path_qs, "t": ctype}, body)
            off = self._end_off
            if self._f is not None:
                self._f.seek(off)
                self._f.write(frame)
                self._f.flush()
            else:
                self._mem_frames[off] = frame
            self._offsets[seq] = (off, len(frame))
            self._end_off = off + len(frame)
            self.last_seq = seq
            spec.emit("append", src=id(self), seq=seq)
        self._fsync_batched()
        self.stats.gauge("replica.wal_bytes", self.size_bytes)
        return seq

    def abort(self, seq: int) -> None:
        """Tombstone a sequenced write that applied NOWHERE (shed before
        any commit, or failed on every group): replay skips it, so a
        recovering group converges to exactly what the live groups hold."""
        with self._mu:
            if self.path is not None and self._f is None:
                raise OSError("write log is closed")
            frame = _encode(seq, {"x": True}, b"")
            off = self._end_off
            if self._f is not None:
                self._f.seek(off)
                self._f.write(frame)
                self._f.flush()
            else:
                self._mem_frames[off] = frame
            self._aborted.add(seq)
            self._offsets.pop(seq, None)
            self._end_off = off + len(frame)
            spec.emit("abort", src=id(self), seq=seq)
        self._fsync_batched()
        self.stats.count("wal.aborted")

    def _fsync_batched(self) -> None:
        """Group commit: block until everything written so far is on
        disk, sharing one fsync between concurrent appenders.

        Compaction swaps the backing file (close + rename), so the fd
        and the target offset are pinned together with ``_file_gen``
        under ``_sync_cv``: a generation bump while waiting means
        ``compact()`` already fsynced everything it kept — and
        everything it dropped was applied by every tracked group — so
        the caller's record is durable (or moot) either way and the
        old-file offsets must never touch ``_synced_off``."""
        if self._f is None or not self.fsync:
            return
        with self._sync_cv:
            target = self._end_off
            gen = self._file_gen
        while True:
            with self._sync_cv:
                if self._file_gen != gen or self._synced_off >= target:
                    return
                if self._syncing:
                    self._sync_cv.wait(0.05)
                    continue
                self._syncing = True
                # Leader: pin the fd and capture the frontier BEFORE
                # the syscall — appends landing during the fsync need
                # the next round, and compact() blocks on _syncing so
                # the fd cannot be closed under the syscall.
                f = self._f
                covered = self._end_off
            try:
                if f is not None:
                    os.fsync(f.fileno())
            finally:
                with self._sync_cv:
                    if self._file_gen == gen:
                        self._synced_off = max(self._synced_off, covered)
                    self._syncing = False
                    self._sync_cv.notify_all()

    # -- read / replay ----------------------------------------------------

    @property
    def first_seq(self) -> int:
        """Lowest LIVE sequence still in the log (0 = empty)."""
        with self._mu:
            return min(self._offsets) if self._offsets else 0

    @property
    def size_bytes(self) -> int:
        return self._end_off

    def records(self, from_seq: int) -> list[WalRecord]:
        """Live records with seq >= from_seq, in sequence order (aborted
        tombstones skipped) — the catch-up suffix."""
        with self._mu:
            seqs = sorted(s for s in self._offsets if s >= from_seq)
            out = []
            for s in seqs:
                off, n = self._offsets[s]
                frame = self._frame_at(off, n)
                payload = frame[_FRAME.size :]
                seq, meta, body = _decode_payload(payload)
                out.append(WalRecord(seq, meta.get("m", ""), meta.get("p", ""),
                                     body, meta.get("t", "")))
            return out

    def _frame_at(self, off: int, n: int) -> bytes:
        if self._f is not None:
            return self._read_at(off, n)
        return self._mem_frames[off]

    # -- compaction -------------------------------------------------------

    def compact(self, min_applied: int) -> int:
        """Drop records (and tombstones) with seq <= ``min_applied`` —
        every tracked group has applied them, so no replay can need
        them.  Atomic for the file-backed log (temp + rename).  Returns
        bytes reclaimed.

        The BULK of the work — copying every kept frame into the temp
        file and fsyncing it — happens OUTSIDE ``_mu``, so appends keep
        flowing to the old file during a large compaction instead of
        stalling behind its disk I/O (the lock checker flags fsync under
        a lock for exactly this reason).  The swap then re-takes ``_mu``,
        appends the DELTA that landed meanwhile (records/tombstones past
        the snapshot), fsyncs that bounded tail, and renames — so the
        new file is durable end to end before the generation bump, which
        preserves the group-commit contract: a bump observed by a
        waiting appender means its record is durable (in bulk or delta)
        or moot (compacted away because every group applied it)."""
        if self._f is None:
            with self._mu:
                keep = sorted(s for s in self._offsets if s > min_applied)
                keep_aborted = {s for s in self._aborted if s > min_applied}
                before = self._end_off
                mem = {}
                offsets = {}
                pos = 0
                for s in keep:
                    off, n = self._offsets[s]
                    fr = self._frame_at(off, n)
                    offsets[s] = (pos, len(fr))
                    mem[pos] = fr
                    pos += len(fr)
                for s in sorted(keep_aborted):
                    fr = _encode(s, {"x": True}, b"")
                    mem[pos] = fr
                    pos += len(fr)
                self._mem_frames = mem
                self._offsets = offsets
                self._end_off = pos
                self._aborted = keep_aborted
                freed = before - self._end_off
                spec.emit("wal_compact", src=id(self), floor=min_applied)
            self.stats.gauge("replica.wal_bytes", self.size_bytes)
            if freed:
                self.stats.count("wal.compactions")
            return freed

        with self._compact_mu:
            # Phase 1 (under _mu): snapshot the kept frames.
            with self._mu:
                if self._f is None:  # closed mid-wait
                    return 0
                snap_last = self.last_seq
                snap_aborted = {s for s in self._aborted if s > min_applied}
                before = self._end_off
                frames = []
                for s in sorted(x for x in self._offsets if x > min_applied):
                    off, n = self._offsets[s]
                    frames.append((s, self._frame_at(off, n)))
            # Phase 2 (no locks): bulk copy + fsync.  Appends land in
            # the old file meanwhile and are carried over as the delta.
            tmp = self.path + ".compact"
            out = open(tmp, "wb")
            offsets = {}
            pos = 0
            for s, fr in frames:
                offsets[s] = (pos, len(fr))
                out.write(fr)
                pos += len(fr)
            for s in sorted(snap_aborted):
                fr = _encode(s, {"x": True}, b"")
                out.write(fr)
                pos += len(fr)
            out.flush()
            if self.fsync:
                os.fsync(out.fileno())
            # Phase 3 (under _mu): append the delta, make it durable,
            # swap.  The delta is bounded by what arrived during phase
            # 2, so this fsync never covers the whole log again.
            with self._mu:
                if self._f is None:  # closed mid-compaction: abandon
                    out.close()
                    os.unlink(tmp)
                    return 0
                for s in sorted(x for x in self._offsets if x > snap_last):
                    off, n = self._offsets[s]
                    fr = self._frame_at(off, n)
                    offsets[s] = (pos, len(fr))
                    out.write(fr)
                    pos += len(fr)
                new_aborts = {s for s in self._aborted if s > min_applied} - snap_aborted
                for s in sorted(new_aborts):
                    fr = _encode(s, {"x": True}, b"")
                    out.write(fr)
                    pos += len(fr)
                    offsets.pop(s, None)  # aborted during phase 2
                out.flush()
                if self.fsync:
                    # analysis-ok's runtime twin: bounded delta fsync
                    # before the rename keeps "gen bump => durable or
                    # moot" true for every waiting appender.
                    with lockcheck.allowed("fsync"):
                        os.fsync(out.fileno())
                out.close()
                # Exclude the group-commit leader for the swap: an
                # in-flight fsync must finish on the OLD fd before it
                # closes, and no new leader may pin the fd mid-swap.
                with self._sync_cv:
                    while self._syncing:
                        self._sync_cv.wait()
                    self._syncing = True
                try:
                    self._f.close()
                    os.replace(tmp, self.path)
                    self._f = open(self.path, "r+b")
                    self._offsets = offsets
                    self._end_off = pos
                finally:
                    with self._sync_cv:
                        # The new file was fsynced end to end (bulk in
                        # phase 2, delta above) before the rename, so
                        # the synced frontier is exactly its end —
                        # never the old file's (larger) offsets, which
                        # would make later appends skip their fsync.
                        self._file_gen += 1
                        self._synced_off = pos
                        self._syncing = False
                        self._sync_cv.notify_all()
                self._aborted = {s for s in self._aborted if s > min_applied}
                freed = before - self._end_off
                spec.emit("wal_compact", src=id(self), floor=min_applied)
        self.stats.gauge("replica.wal_bytes", self.size_bytes)
        if freed:
            self.stats.count("wal.compactions")
        return freed

    def close(self) -> None:
        with self._mu:
            if self._f is None:
                return
            # Same swap discipline as compact(): wait out an in-flight
            # group-commit fsync, then bump the generation so waiting
            # followers return instead of spinning on a dead frontier.
            with self._sync_cv:
                while self._syncing:
                    self._sync_cv.wait()
                self._syncing = True
            try:
                self._f.close()
                self._f = None
            finally:
                with self._sync_cv:
                    self._file_gen += 1
                    self._syncing = False
                    self._sync_cv.notify_all()
