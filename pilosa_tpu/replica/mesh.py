"""Device-mesh construction for one replica serving group.

One group's device plane is the 2-D ``(slice, replica)`` mesh the
collectives already prove (parallel/sharded.py ReplicaMesh,
tests/test_multihost.py::test_lockstep_four_ranks_replica_mesh): the
``slice`` axis shards the bitmap stacks, the ``replica`` axis holds
full copies that split read batches.  What this module decides is the
PHYSICAL layout:

- MULTIHOST (a joined ``jax.distributed`` job spanning pods): the
  hybrid T5X-style layout via ``mesh_utils.create_hybrid_device_mesh``
  (SNIPPETS.md [1]) — the replica axis rides DCN between pods while
  every slice-axis psum stays on ICI inside a pod, the multi-pod shape
  BACKLOG.md prescribes.
- SINGLE PROCESS (CPU rigs, tests, one-host TPU boxes): a flat 2-D
  reshape; there is no DCN topology to exploit, and
  ``create_hybrid_device_mesh`` cannot even build (it needs >= 2 DCN
  granules) — ReplicaMesh's guarded fallback handles a hybrid request
  gracefully, but asking for the flat layout directly skips the probe.
"""

from __future__ import annotations

from typing import Optional, Sequence


def build_group_mesh(n_replicas: int = 2, devices: Optional[Sequence] = None,
                     hybrid: Optional[bool] = None):
    """Build the (slice x replica) mesh for one serving group.

    ``hybrid=None`` (the default) decides from the job shape: hybrid
    when this process is part of a multi-process ``jax.distributed``
    job (replica axis on DCN), flat otherwise.  Returns a
    :class:`~pilosa_tpu.parallel.multihost.MultiHostReplicaMesh` in the
    multihost case (slice-ownership helpers included) and a plain
    :class:`~pilosa_tpu.parallel.sharded.ReplicaMesh` otherwise.
    """
    import jax

    multihost = jax.process_count() > 1
    if hybrid is None:
        hybrid = multihost
    if multihost:
        from pilosa_tpu.parallel.multihost import MultiHostReplicaMesh

        return MultiHostReplicaMesh(
            n_replicas=n_replicas, devices=devices, hybrid=hybrid
        )
    from pilosa_tpu.parallel.sharded import ReplicaMesh

    return ReplicaMesh(n_replicas=n_replicas, devices=devices, hybrid=hybrid)
