"""Group catch-up: replaying the missed WAL suffix to a recovering group.

The contract that makes degraded-quorum writes safe is REPLAYABILITY:
any write a group missed (down, lagging, or shed under load) can be
re-delivered, in the original total order, until the group's applied
state is identical to its siblings'.  Two halves live here:

GROUP SIDE — :class:`AppliedSeq` tracks the highest router-assigned
write sequence this group has applied (the ``X-Pilosa-Write-Seq``
request header, noted once the route answered deterministically) and
persists it next to the data so a RESTARTED group reports where it
left off instead of zero.  The group reports it on every response
(``X-Pilosa-Applied-Seq``, beside ``X-Pilosa-Group``) and in the
``/replica/health`` JSON — the router's passive lag tracking and the
probe's catch-up trigger.  Persistence is write-behind of the data
itself, so after a crash the number can UNDERcount: replay then
re-applies a short suffix the group already holds — harmless, because
every sequenced write is idempotent at the group (SetBit/import
re-apply cleanly; schema mutations answer deterministic 409/404 which
catch-up counts as applied).

ROUTER SIDE — :class:`CatchupManager` streams ``wal.records(applied+1)``
to a recovering group over the router's own forward path, in order,
each tagged with its sequence (``X-Pilosa-Write-Seq``) and the replay
marker (``X-Pilosa-Replay: 1`` — the group tags sampled trace roots
``replay=true`` so replayed traffic is distinguishable in
``/debug/traces``).  EPOCH GUARD: the round pins the group's epoch at
start; if any replay response reports a different epoch the group
restarted MID-replay — the round aborts immediately (counted
``replica.catchup_abort``) rather than keep feeding a new incarnation
writes sequenced against the old one's applied state; the next probe
reads the fresh incarnation's applied_seq and starts over.  The final
records are replayed under the router's sequencer lock so no write can
slip between "drained the suffix" and "rejoined the rotation" — only a
FULLY caught-up group starts taking reads again, preserving the
cross-group read-your-writes invariant.  That locked hold is
DEADLINE-BOUND (``locked_drain_s``; ``replica.catchup_stall`` counted
on expiry): a group that turns slow or hangs mid-drain aborts the
round instead of stalling every write cluster-wide.

RESYNC HANDOFF (PR 9): the automated resync (replica/resync.py) uses
this manager as its final leg — after streaming a stale or blank group
the donor's fragments it seeds the group's ``AppliedSeq`` to the
donor's sequence (``POST /replica/seed-seq``, monotonic via
:meth:`AppliedSeq.note`) and calls :meth:`CatchupManager.catch_up` to
replay the short remainder, so "rejoined" always means byte-identical
AND caught up regardless of which path brought the group back.
"""

from __future__ import annotations

import os
import threading

from pilosa_tpu.analysis import lockcheck
import time
from typing import Optional

from pilosa_tpu.stats import NOP_STATS


class AppliedSeq:
    """The group's high-water mark of applied router write sequences.

    ``path=None`` keeps it in memory (embedders, tests); with a path the
    value is persisted via atomic replace on every advance, so a
    restarted group resumes from (at most a hair under) where it
    stopped."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._mu = lockcheck.named_lock("replica.appliedseq._mu")
        self.value = 0
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self.value = int(f.read().strip() or 0)
            except (OSError, ValueError):
                self.value = 0

    def note(self, seq: int) -> None:
        """Record that write ``seq`` was applied (monotonic max)."""
        with self._mu:
            if seq <= self.value:
                return
            self.value = seq
            if self.path:
                tmp = self.path + ".tmp"
                try:
                    with open(tmp, "w") as f:
                        f.write(str(seq))
                    os.replace(tmp, self.path)
                except OSError:
                    pass  # persistence is best-effort; replay re-converges


def note_applied_from_headers(applied: Optional[AppliedSeq], headers: dict,
                              status: int, retry_after=None) -> None:
    """Group-side helper: advance the applied mark when a request carried
    the router's write-sequence header and the route answered
    DETERMINISTICALLY — 2xx (applied) or a deterministic 4xx (the write
    answers identically on every group: 409 index-exists on a replayed
    create, 400 parse errors).  The decision is the SHARED
    :func:`pilosa_tpu.replica.write_not_applied` predicate — identical
    to the router's fan-out and replay rules, so a shed expressed as a
    <500 status carrying Retry-After (pass ``retry_after`` from the
    response) never advances a mark the router considers not applied."""
    from pilosa_tpu.replica import write_not_applied

    if applied is None:
        return
    raw = headers.get("x-pilosa-write-seq")
    if not raw:
        return
    if write_not_applied(status, retry_after):
        return
    try:
        applied.note(int(raw))
    except (TypeError, ValueError):
        pass


class CatchupManager:
    """Streams the missed WAL suffix to recovering groups (router side)."""

    def __init__(self, router, wal, stats=None, drain_batch: int = 64,
                 locked_drain_s: float = 5.0, budgets=None):
        self.router = router
        self.wal = wal
        self.stats = stats if stats is not None else NOP_STATS
        # Adaptive drain budget (planner.AdaptiveBudgets): when the
        # router wires one, each round sizes the locked phase from the
        # MEASURED per-record replay cost (observed below) instead of
        # the static drain_batch — fast links drain more under the lock,
        # slow ones less, both inside locked_drain_s.
        self.budgets = budgets
        # Records replayed per loop iteration OUTSIDE the sequencer
        # lock; the final <= drain_batch records replay under it so the
        # rejoin flip races no concurrent write.  That locked phase is
        # DEADLINE-BOUND (locked_drain_s, shared across its records,
        # each socket capped at the remainder): a slow or hanging
        # recovering group must not stall every write cluster-wide —
        # past the bound the round aborts, the group keeps its
        # applied_seq progress, and the next probe retries with a
        # shorter suffix.
        self.drain_batch = drain_batch
        self.locked_drain_s = locked_drain_s

    def needed(self, g) -> bool:
        return g.applied_seq < self.wal.last_seq

    def _replay_one(self, g, rec, start_epoch: str,
                    timeout_s: Optional[float] = None) -> bool:
        """Forward one WAL record to ``g``; returns True when the group
        applied (or deterministically answered) it AND its epoch still
        matches the round's.  ``timeout_s`` caps the socket (the locked
        drain's remaining deadline)."""
        from pilosa_tpu.replica import (
            GROUP_HEADER,
            REPLAY_HEADER,
            WRITE_SEQ_HEADER,
            write_not_applied,
        )

        self.router.faults.hit("catchup", key=g.name)
        headers = {WRITE_SEQ_HEADER: str(rec.seq), REPLAY_HEADER: "1"}
        if rec.ctype:
            headers["content-type"] = rec.ctype
        t_fwd = time.perf_counter()
        try:
            status, _ctype, _payload, rheaders = self.router._forward(
                g, rec.method, rec.path, rec.body, headers,
                timeout_s=timeout_s,
            )
        except OSError:
            return False
        finally:
            if self.budgets is not None:
                # Feed the measured replay cost back under the "catchup"
                # budget lane — the next round's drain batch reads it.
                self.budgets.observe_transfer(
                    "catchup", (time.perf_counter() - t_fwd) * 1e3,
                    len(rec.body or b""),
                )
        hdr_epoch = rheaders.get(GROUP_HEADER)
        if (start_epoch is not None and hdr_epoch is not None
                and hdr_epoch != start_epoch):
            # The group restarted mid-replay: a fresh incarnation must
            # not absorb a stream paced against the old one's state.
            self.stats.count("replica.catchup_abort")
            return False
        # The SAME "did it land?" predicate as the write fan-out and
        # the group-side bookkeeping — a shed-shaped answer (<500 with
        # Retry-After) must not advance the mark here while the fan-out
        # counts the identical answer as not applied.
        if write_not_applied(status, rheaders.get("Retry-After")):
            return False
        # Monotonic-max under the router's table lock: replay runs on
        # the probe thread while handler threads note applied marks off
        # live responses — an unguarded read-modify-write here can drop
        # the higher mark (lockset-race declared on GroupState).
        from pilosa_tpu.analysis import spec

        with self.router._mu:
            g.applied_seq = max(g.applied_seq, rec.seq)
            spec.emit("apply", src=id(self.wal), group=g.name, seq=rec.seq,
                      ok=status < 300, replay=True)
        self.stats.count("replica.replayed")
        return True

    def catch_up(self, g) -> bool:
        """Run one full catch-up round for ``g`` (probe thread).  On
        success the group is fully converged and flipped back into the
        read/write rotation atomically w.r.t. the sequencer; on any
        failure the group stays out and the next probe retries."""
        start_epoch = g.epoch
        self.stats.count("replica.catchup_rounds")
        t0 = time.perf_counter()
        # Effective locked-phase record budget: measured (clamped) when
        # the adaptive budgets have replay samples, static otherwise.
        batch = (
            self.budgets.catchup_drain_batch()
            if self.budgets is not None
            else self.drain_batch
        )
        # Phase 1: drain the bulk of the suffix without blocking writes.
        while True:
            recs = self.wal.records(g.applied_seq + 1)
            if len(recs) <= batch:
                break
            for rec in recs[: -batch]:
                if not self._replay_one(g, rec, start_epoch):
                    return False
        # Phase 2: the short remainder under the sequencer lock — no new
        # write can be sequenced while the group drains to the head and
        # rejoins, so rejoining == fully caught up, always.  The lock
        # hold is DEADLINE-BOUND: a group that turned slow mid-round
        # (default socket timeout × drain_batch could stall writes for
        # minutes) aborts the round instead — it keeps its applied_seq
        # progress and the next probe retries the shorter remainder.
        with self.router._seq_mu:
            limit = time.monotonic() + self.locked_drain_s
            for rec in self.wal.records(g.applied_seq + 1):
                left = limit - time.monotonic()
                if left <= 0:
                    self.stats.count("replica.catchup_stall")
                    return False
                if not self._replay_one(g, rec, start_epoch, timeout_s=left):
                    return False
            with self.router._mu:
                g.caught_up = True
        self.stats.timing("replica.catchup_ms", (time.perf_counter() - t0) * 1e3)
        return True
