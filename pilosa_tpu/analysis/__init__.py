"""Project invariant linter + debug-mode runtime concurrency checker.

``python -m pilosa_tpu.analysis`` runs five project-specific rules over
the live tree and exits nonzero on NEW findings (a checked-in baseline
grandfathers accepted pre-existing violations; ``# analysis-ok: <rule>:
<reason>`` suppresses a site explicitly):

1. lockstep-determinism — rank-local nondeterminism reachable from the
   lockstep batch-execution entry points;
2. lock-discipline — raw ``threading.Lock()``/``RLock()``/``Condition()``
   instantiations that bypass the instrumented :mod:`.lockcheck`
   factories (the runtime half of this rule is the
   ``PILOSA_TPU_LOCK_CHECK=1`` checker);
3. stats-registry — every stats name must appear in the generated
   counters registry (COUNTERS.md), which must match the tree;
4. exception-hygiene — ``except Exception`` must record a stat, use the
   exception, re-raise, or carry a tag;
5. deadline-propagation — functions holding a deadline that perform an
   HTTP hop must forward the remaining budget.

This module stays import-light: serving modules import
``pilosa_tpu.analysis.lockcheck`` at startup, so nothing here may pull
in the linter machinery (or anything heavy) at import time.
"""

from __future__ import annotations

__all__ = ["run_analysis", "Finding", "RULES"]


def __getattr__(name):
    if name in __all__:
        from pilosa_tpu.analysis import engine

        return getattr(engine, name)
    raise AttributeError(name)
