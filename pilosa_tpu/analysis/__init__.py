"""Project invariant linter + debug-mode runtime concurrency checker.

``python -m pilosa_tpu.analysis`` runs eight project-specific rules
over the live tree and exits nonzero on NEW findings (a checked-in
baseline grandfathers accepted pre-existing violations; ``# analysis-ok:
<rule>: <reason>`` suppresses a site explicitly):

1. lockstep-determinism — rank-local nondeterminism reachable from the
   lockstep batch-execution entry points;
2. lock-discipline — raw ``threading.Lock()``/``RLock()``/``Condition()``
   instantiations that bypass the instrumented :mod:`.lockcheck`
   factories (the runtime half of this rule is the
   ``PILOSA_TPU_LOCK_CHECK=1`` checker);
3. stats-registry — every stats name must appear in the generated
   counters registry (COUNTERS.md), which must match the tree;
4. exception-hygiene — ``except Exception`` must record a stat, use the
   exception, re-raise, or carry a tag;
5. deadline-propagation — functions holding a deadline that perform a
   budget-carrying hop (executor→client, or the replica forward paths)
   must forward the remaining budget (``deadline=`` / ``timeout_s=``);
6. guarded-fields — fields declared in a class's ``_guarded_by_`` map
   mutated in methods with no named-lock acquisition on any call path
   (the static half of lockcheck's Eraser-style lockset race detector);
7. native-abi — the ctypes bridge vs the ``extern "C"`` definitions vs
   the built .so's exports: missing symbols, arity and integer-width
   mismatches (:mod:`.abi`);
8. stale-suppression — ``analysis-ok`` tags whose rule no longer fires
   at their site (the suppression set must not rot as code moves).

Beside the lint rules, the DYNAMIC analysis lane (generation 3):
``--explore`` drives the deterministic interleaving explorer
(:mod:`.sched` + the scenario registry in :mod:`.scenarios`) —
cooperative schedule control over real project code, exhaustive under
a preemption bound, every failure replayable from a printed schedule
string — and the replica write-protocol model / trace-conformance /
linearizability checkers (:mod:`.spec`).

This module stays import-light: serving modules import
``pilosa_tpu.analysis.lockcheck`` (and the zero-cost
``pilosa_tpu.analysis.spec`` event seam) at startup, so nothing here
may pull in the linter machinery (or anything heavy) at import time.
"""

from __future__ import annotations

__all__ = ["run_analysis", "Finding", "RULES"]


def __getattr__(name):
    if name in __all__:
        from pilosa_tpu.analysis import engine

        return getattr(engine, name)
    raise AttributeError(name)
