"""Debug-mode runtime concurrency checker for the project's named locks.

The replica/WAL tier is lock-heavy threaded code where the last two
review rounds each found hand-caught races (the PR 6 inflight-gauge
race, the PR 7 fsync-under-compaction swap).  This module turns the
conventions those fixes rely on into a checkable model, the way Go's
race detector did for the reference Pilosa:

- every interesting lock is created through :func:`named_lock` /
  :func:`named_rlock` / :func:`named_condition` and carries a stable
  NAME ("replica.router._seq_mu", "replica.wal._mu", ...);
- with ``PILOSA_TPU_LOCK_CHECK=1`` (or an explicit :func:`enable`)
  the factories return instrumented wrappers that feed a global
  checker; otherwise they return plain ``threading`` primitives with
  zero overhead;
- the checker builds the cross-thread lock acquisition-order graph
  (edges by lock NAME, so every fragment's ``_mu`` is one node) and
  records a violation when a new acquisition closes a cycle — the
  classic potential-deadlock witness, caught even when the interleaving
  that would actually deadlock never happens in the run;
- blocking calls (``os.fsync``, socket I/O, ``subprocess``) executed
  while ANY checked lock is held are violations unless the (lock,
  kind) pair is allowlisted — either in :data:`DEFAULT_ALLOW_PAIRS`
  (documented by-design holds, e.g. the write sequencer fanning out
  over HTTP) or via a code-local ``with allowed("fsync"):`` scope;
- GENERATION 2 — an Eraser-style LOCKSET RACE DETECTOR over declared
  guarded state: classes carry ``_guarded_by_ = {"field": "lock.name"}``
  and register with :func:`guarded_class` (or individual objects via
  :func:`guarded`); while the checker is enabled their ``__setattr__``
  is instrumented, and every write to a declared field refines a
  per-(object, field) CANDIDATE LOCKSET — the intersection of the
  named locks held at each write.  Writes by the first (and only)
  accessing thread are exempt (the init-phase single-thread state:
  construction and ``open()`` predate sharing); the lockset
  initializes at the first write from a SECOND thread and shrinks by
  intersection from there.  An empty lockset with >= 2 observed
  threads is a ``lockset-race`` violation carrying the first shared
  write's stack and the emptying write's stack — the data-race analog
  of the order graph's first-witness cycles, and the safety net the
  free-threaded multi-core refactor needs (lock-order checking alone
  only catches deadlocks, ROADMAP item 2).  Only attribute REBINDS are
  seen (``self.f = ...``, ``self.f += ...``); in-place container
  mutation is covered by the static ``guarded-fields`` companion rule
  (analysis/rules.py) instead.

Violations are RECORDED, not raised at the faulting site (raising
inside a background probe thread would be swallowed by its own
error handling); tests drain them with :func:`take_violations` or
assert emptiness with :func:`check`.  tests/conftest.py enables the
checker for the tier-1 concurrency/replica/qos suites and fails any
test that recorded a violation.

Re-entrant acquisition of the same named lock is tracked by depth and
never creates a self-edge: instances sharing a name (every fragment's
``_mu``) cannot be ordered against each other by name alone, so
same-name nesting is out of the model's scope.
"""

from __future__ import annotations

import os
import socket
import subprocess
import threading
import traceback
import weakref

ENV_VAR = "PILOSA_TPU_LOCK_CHECK"

# (lock name, blocking kind) pairs that are BY DESIGN: holding the
# named lock across this class of blocking call is the documented
# serialization contract, not an accident.  Keep this list short and
# justified — every entry is a place a slow syscall stalls every other
# user of the lock.
DEFAULT_ALLOW_PAIRS: frozenset[tuple[str, str]] = frozenset(
    {
        # The write sequencer IS the total order: the router holds
        # _seq_mu across the whole HTTP fan-out so every group applies
        # every write in the same sequence (replica/router.py), and
        # catch-up's phase-2 locked drain replays the final records
        # under the same lock so rejoin == fully-caught-up.  The WAL
        # append + group-commit fsync sit inside the same hold: a
        # write's durability point is part of its slot in the order.
        ("replica.router._seq_mu", "socket"),
        ("replica.router._seq_mu", "fsync"),
        # _compact_mu exists ONLY to serialize whole compactions; the
        # bulk copy + fsync run under it by construction, off the
        # append path (appenders take _mu, which the bulk phase does
        # NOT hold — that is the point of the split).
        ("replica.wal._compact_mu", "fsync"),
        # Lockstep rank 0 ships batch entries to the worker sockets
        # while holding the order lock — the ship IS the point where
        # the total order is fixed (parallel/service.py).
        ("lockstep._order_mu", "socket"),
        ("lockstep._q_cv", "socket"),
    }
)

BLOCKING_KINDS = ("fsync", "socket", "subprocess")


class LockCheckError(AssertionError):
    """A recorded lock-discipline violation, raised by check()."""


def _stack(skip: int = 2) -> str:
    return "".join(traceback.format_stack()[:-skip][-8:])


class Violation:
    __slots__ = ("kind", "detail", "thread", "stack")

    def __init__(self, kind: str, detail: str, stack: str):
        self.kind = kind
        self.detail = detail
        self.thread = threading.current_thread().name
        self.stack = stack

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Violation {self.kind}: {self.detail} [{self.thread}]>"

    def describe(self) -> str:
        return f"{self.kind}: {self.detail}\n  thread: {self.thread}\n{self.stack}"


class _FieldRecord:
    """Eraser state for one (object, field) location.

    ``lockset`` is None while the location is still in its exclusive
    (single-thread init) phase; it initializes to the held-lock set of
    the first write from a SECOND thread and only ever shrinks by
    intersection afterwards."""

    __slots__ = ("ref", "first_tid", "threads", "lockset", "first_stack",
                 "reported")

    def __init__(self, ref, tid: int, stack: str):
        self.ref = ref  # weakref to the owning object (stale-id guard)
        self.first_tid = tid
        self.threads = {tid}
        self.lockset = None
        self.first_stack = stack
        self.reported = False


class _Checker:
    """Global acquisition-order graph + held-lock bookkeeping."""

    def __init__(self):
        self._mu = threading.Lock()  # leaf lock: guards graph/violations only
        # edge a -> b: lock named a was held while b was acquired;
        # value = first-witness stack for the report.
        self._edges: dict[str, dict[str, str]] = {}
        self._violations: list[Violation] = []
        self._seen_cycles: set[tuple[str, str]] = set()
        self._seen_blocking: set[tuple[str, str]] = set()
        # (id(obj), field) -> _FieldRecord for the lockset race detector.
        self._fields: dict[tuple[int, str], _FieldRecord] = {}
        self._tls = threading.local()
        self.allow_pairs: set[tuple[str, str]] = set(DEFAULT_ALLOW_PAIRS)

    # -- per-thread held stack -------------------------------------------

    def _held(self) -> list[list]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []  # [name, depth] entries, acquisition order
        return h

    def _scoped_allows(self) -> list[str]:
        a = getattr(self._tls, "allows", None)
        if a is None:
            a = self._tls.allows = []
        return a

    def note_acquired(self, name: str) -> None:
        held = self._held()
        for e in held:
            if e[0] == name:
                e[1] += 1  # re-entrant: no new edge, no self-edge
                return
        if held:
            holders = [e[0] for e in held if e[0] != name]
            if holders:
                with self._mu:
                    for a in holders:
                        fresh = name not in self._edges.get(a, ())
                        self._edges.setdefault(a, {}).setdefault(name, _stack())
                        if fresh:
                            self._check_cycle(a, name)
        held.append([name, 1])

    def note_released(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                held[i][1] -= 1
                if held[i][1] == 0:
                    del held[i]
                return

    def held_names(self) -> list[str]:
        return [e[0] for e in self._held()]

    # -- cycle detection ---------------------------------------------------

    def _check_cycle(self, a: str, b: str) -> None:
        """Adding edge a->b: a path b ->* a means a cycle through (a, b).
        Called under self._mu."""
        path = self._find_path(b, a)
        if path is None:
            return
        key = (a, b) if a < b else (b, a)
        if key in self._seen_cycles:
            return
        self._seen_cycles.add(key)
        cycle = [a] + path
        self._violations.append(
            Violation(
                "lock-order-cycle",
                " -> ".join(cycle)
                + f" (new edge {a} -> {b} closes the cycle; first-witness "
                f"stacks in the acquisition-order graph)",
                _stack(),
            )
        )

    def _find_path(self, src: str, dst: str) -> list[str] | None:
        """DFS src ->* dst over recorded edges; returns the node path."""
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- blocking calls ----------------------------------------------------

    def note_blocking(self, kind: str) -> None:
        held = self._held()
        if not held:
            return
        if kind in self._scoped_allows():
            return
        bad = [e[0] for e in held if (e[0], kind) not in self.allow_pairs]
        if not bad:
            return
        key = (tuple(bad)[0], kind)
        with self._mu:
            if key in self._seen_blocking:
                return
            self._seen_blocking.add(key)
            self._violations.append(
                Violation(
                    "blocking-under-lock",
                    f"{kind} call while holding {', '.join(bad)}",
                    _stack(),
                )
            )

    # -- lockset race detection (declared guarded fields) -----------------

    def note_field_write(self, obj, cls_name: str, field: str,
                         lockname: str) -> None:
        """One write to a declared-guarded field: refine the location's
        candidate lockset (Eraser's C(v) &= locks_held), with the
        init-phase single-thread exemption."""
        tid = threading.get_ident()
        key = (id(obj), field)
        held = None
        with self._mu:
            rec = self._fields.get(key)
            if rec is not None and rec.ref() is not obj:
                rec = None  # id was recycled by a dead object: fresh record
            if rec is None:
                try:
                    ref = weakref.ref(obj)
                except TypeError:  # pragma: no cover - no __weakref__ slot
                    ref = lambda _o=None: obj  # noqa: E731 — pins obj; rare
                self._fields[key] = _FieldRecord(ref, tid, _stack())
                return
            rec.threads.add(tid)
            if len(rec.threads) == 1:
                return  # exclusive phase: only the first thread has written
            held = set(self.held_names())
            if rec.lockset is None:
                # First write after the location became shared: the
                # candidate set starts as exactly what this write holds.
                rec.lockset = held
            else:
                rec.lockset &= held
            if not rec.lockset and not rec.reported:
                rec.reported = True
                self._violations.append(
                    Violation(
                        "lockset-race",
                        f"{cls_name}.{field} (declared guarded by "
                        f"{lockname}): write with EMPTY candidate lockset — "
                        f"{len(rec.threads)} threads observed, no common "
                        "named lock across their writes\n"
                        "  first-witness (earliest recorded write):\n"
                        + rec.first_stack,
                        _stack(),
                    )
                )

    # -- reporting ---------------------------------------------------------

    def take_violations(self) -> list[Violation]:
        with self._mu:
            out = self._violations
            self._violations = []
            return out

    def reset(self) -> None:
        """Clear the graph and pending violations (per-test isolation:
        two tests acquiring A->B and B->A respectively never interleave,
        so cross-test edges would be false cycles)."""
        with self._mu:
            self._edges = {}
            self._violations = []
            self._seen_cycles = set()
            self._seen_blocking = set()
            self._fields = {}


_checker = _Checker()
_enabled = False
_patched = False
_orig: dict[str, object] = {}

# Cooperative-scheduler seam (analysis/sched.py): while an exploration
# run is active, the named factories delegate primitive construction to
# the scheduler (so every lock/condition a scenario builds is a yield
# point), guarded-field writes yield BEFORE the write lands (the
# interleaving that loses an unlocked read-modify-write only exists if
# control can change hands between the read and the write), and the
# blocking-call patches yield at each crossing.  None = zero overhead.
_sched = None


def set_sched(hook) -> None:
    """Install (or clear, with None) the active exploration scheduler."""
    global _sched
    _sched = hook


def sched_hook():
    return _sched


def checker() -> _Checker:
    return _checker


def enabled() -> bool:
    return _enabled


# -- instrumented primitives ----------------------------------------------


class CheckedLock:
    """threading.Lock wrapper feeding the global checker."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._inner = self._make_inner()

    def _make_inner(self):
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _checker.note_acquired(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        _checker.note_released(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<CheckedLock {self.name} {self._inner!r}>"


class CheckedRLock(CheckedLock):
    """threading.RLock wrapper; recursion tracked by depth, and the
    Condition integration hooks (_release_save/_acquire_restore/
    _is_owned) keep the held bookkeeping correct across cv.wait()."""

    _reentrant = True

    def _make_inner(self):
        return threading.RLock()

    def _release_save(self):
        # Fully release the recursion for a cv.wait(): drop our
        # bookkeeping entirely, remember nothing (the inner state
        # carries the depth).
        state = self._inner._release_save()
        _checker.note_released(self.name)
        held = _checker._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                del held[i]
                break
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        _checker.note_acquired(self.name)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def named_lock(name: str):
    """A mutex participating in the order/blocking checks when the
    checker is enabled; a plain threading.Lock otherwise.  Under an
    active exploration run (analysis/sched.py) the scheduler supplies
    the primitive so every acquisition is a controlled yield point."""
    s = _sched
    if s is not None:
        return s.make_lock(name)
    if _enabled:
        return CheckedLock(name)
    return threading.Lock()


def named_rlock(name: str):
    s = _sched
    if s is not None:
        return s.make_rlock(name)
    if _enabled:
        return CheckedRLock(name)
    return threading.RLock()


def named_condition(name: str, lock=None):
    """A Condition whose underlying lock is checked when enabled.
    ``lock`` reuses an existing (possibly checked) lock, as in
    ``Condition(self._mu)``."""
    s = _sched
    if s is not None:
        return s.make_condition(name, lock)
    if lock is not None:
        return threading.Condition(lock)
    if _enabled:
        return threading.Condition(CheckedLock(name))
    return threading.Condition()


class allowed:
    """Scoped, code-local allowlist entry: the blocking call inside is
    a documented part of the holding lock's contract.

    with lockcheck.allowed("fsync"):   # bounded delta fsync before swap
        os.fsync(fd)
    """

    def __init__(self, *kinds: str):
        self.kinds = kinds

    def __enter__(self):
        _checker._scoped_allows().extend(self.kinds)
        return self

    def __exit__(self, *exc) -> None:
        a = _checker._scoped_allows()
        for k in self.kinds:
            if k in a:
                a.remove(k)


# -- guarded-state declarations (lockset race detector) ---------------------
#
# Classes declare which named lock guards which field:
#
#     @lockcheck.guarded_class
#     class Fragment:
#         _guarded_by_ = {"storage": "core.fragment._mu", ...}
#
# With the checker enabled, the class's __setattr__ is wrapped so every
# write to a declared field feeds note_field_write(); disabled, the
# class is left untouched (zero overhead).  guarded(obj, attr, lock=..)
# registers a single object's field instead (ad-hoc shared state that
# has no class-level contract).

_GUARDED_CLASSES: list = []
# Classes with at least one per-instance guarded() registration; the
# wrapper only consults the instance table for these.
_INSTANCE_GUARDED_TYPES: set = set()
_instance_guards: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_SETATTR_SENTINEL = "__lockcheck_wrapped_setattr__"


def _patch_guarded_class(cls) -> None:
    if _SETATTR_SENTINEL in cls.__dict__:
        return
    own = cls.__dict__.get("__setattr__")  # restore target (None = inherited)
    base_setattr = cls.__setattr__
    decl = dict(getattr(cls, "_guarded_by_", ()) or ())
    cls_name = cls.__name__

    def checked_setattr(self, name, value):
        lock = decl.get(name)
        if lock is None and type(self) in _INSTANCE_GUARDED_TYPES:
            ig = _instance_guards.get(self)
            if ig is not None:
                lock = ig.get(name)
        if lock is not None:
            s = _sched
            if s is not None:
                # Exploration yield point BEFORE the write lands: the
                # schedule that loses an unlocked read-modify-write
                # needs a context switch between the read (already
                # evaluated into ``value``) and this store.
                s.field_write(self, cls_name, name)
        base_setattr(self, name, value)
        if lock is not None and _enabled:
            _checker.note_field_write(self, cls_name, name, lock)

    checked_setattr.__lockcheck_orig__ = own
    setattr(cls, "__setattr__", checked_setattr)
    setattr(cls, _SETATTR_SENTINEL, True)


def _unpatch_guarded_class(cls) -> None:
    wrapped = cls.__dict__.get("__setattr__")
    if _SETATTR_SENTINEL not in cls.__dict__ or wrapped is None:
        return
    orig = getattr(wrapped, "__lockcheck_orig__", None)
    if orig is None:
        delattr(cls, "__setattr__")  # was inherited (object.__setattr__)
    else:
        setattr(cls, "__setattr__", orig)
    delattr(cls, _SETATTR_SENTINEL)


def guarded_class(cls):
    """Class decorator registering ``cls._guarded_by_`` declarations
    with the lockset race detector.  A no-op marker while the checker
    is disabled; instrumented from :func:`enable` on (including classes
    defined after enable — subprocess workers self-enable at import,
    before the guarded modules load)."""
    if cls not in _GUARDED_CLASSES:
        _GUARDED_CLASSES.append(cls)
    if _enabled or _sched is not None:
        _patch_guarded_class(cls)
    return cls


def guarded(obj, attr: str, lock: str) -> None:
    """Register ONE object's field as guarded by the named lock — the
    ad-hoc twin of a class-level ``_guarded_by_`` entry.  The object's
    class joins the instrumentation set (its declared dict, if any,
    still applies)."""
    cls = type(obj)
    _INSTANCE_GUARDED_TYPES.add(cls)
    ig = _instance_guards.get(obj)
    if ig is None:
        ig = _instance_guards[obj] = {}
    ig[attr] = lock
    guarded_class(cls)


# -- named globals (registered module-level mutable state) -------------------
#
# GENERATION 3 — the sanctioned seam for module-level mutable state in
# serving-reachable code (the free-threading readiness contract,
# ROADMAP item 2).  A bare module-level memo dict relies on the GIL for
# every one of its compound operations; the static
# ``global-mutable-state`` rule (analysis/rules.py) flags those, and
# this factory is the fix it points at:
#
#     _PARSE_MEMO = lockcheck.named_global("pql.parse_memo",
#                                          max_entries=512)
#
# Each NamedGlobal is a bounded LRU mapping whose every mutation runs
# under its own NAMED lock (so the order/blocking checks see it), is
# registered in a process-wide registry (``named_globals()`` — the
# debug inventory, and the /metrics publication seam), and feeds the
# lockset race detector on every mutation: a future code path that
# mutated the store without the named lock empties the per-(object,
# field) candidate lockset exactly like an undisciplined guarded-field
# write.  Under an active exploration run the memo BYPASSES itself
# (every get is a miss, every put a no-op) so execution #1 and #N of a
# scenario have identical yield structure — this is what retires the
# PR 12 driver-thread warm-up workaround in analysis/scenarios.py.

_named_globals: dict[str, "NamedGlobal"] = {}
_named_globals_mu = threading.Lock()  # leaf: guards the registry dict only


class _GlobalLock:
    """The mutex inside a NamedGlobal.  Module-level globals are built
    at import time — usually BEFORE enable() runs in a test process —
    so unlike named_lock() this wrapper consults the enable state per
    acquisition instead of freezing it at construction: the same
    process-lifetime lock is invisible in production and fully checked
    the moment the checker turns on."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.Lock()

    def __enter__(self):
        self._inner.acquire()
        if _enabled:
            _checker.note_acquired(self.name)
        return self

    def __exit__(self, *exc) -> None:
        # Unconditional: note_released tolerates a name it never saw
        # acquired (enable() flipping mid-hold must not strand a held
        # entry on this thread).
        _checker.note_released(self.name)
        self._inner.release()


class NamedGlobal:
    """A registered, bounded, lock-named LRU — the only sanctioned
    shape for module-level mutable state on serving paths.  Values are
    computed OUTSIDE the lock by the caller (get -> miss -> compute ->
    put), so a slow fill never serializes readers; the worst case of
    two racing fills is a double compute with last-writer-wins, never
    a torn structure."""

    def __init__(self, name: str, max_entries: int = 256,
                 max_key_len: int = 0):
        self.name = name
        self.max_entries = int(max_entries)
        # 0 = unbounded; nonzero keys longer than this bypass the memo
        # entirely (don't pin megabyte bodies).
        self.max_key_len = int(max_key_len)
        self._mu = _GlobalLock(name)
        self._store: "dict" = {}
        self.stat_hits = 0
        self.stat_misses = 0
        self.stat_evictions = 0
        # Lockset-detector registration: a rebind of the store without
        # the named lock is a violation like any guarded field.
        guarded(self, "_store", lock=name)

    def _note_mutation(self) -> None:
        """Feed the lockset detector one store mutation (called with
        ``self._mu`` held, so the candidate lockset always contains the
        global's own name on disciplined paths)."""
        if _enabled:
            _checker.note_field_write(self, "NamedGlobal", "_store", self.name)

    def _bypass(self, key) -> bool:
        if _sched is not None:
            return True  # exploration: identical structure every execution
        return bool(self.max_key_len) and len(key) > self.max_key_len

    def get(self, key, default=None):
        if self._bypass(key):
            return default
        with self._mu:
            try:
                v = self._store.pop(key)
            except KeyError:
                self.stat_misses += 1
                return default
            self._store[key] = v  # re-insert = move to MRU end
            self.stat_hits += 1
            return v

    def put(self, key, value) -> None:
        if self._bypass(key):
            return
        with self._mu:
            self._store.pop(key, None)
            self._store[key] = value
            while len(self._store) > self.max_entries:
                self._store.pop(next(iter(self._store)))
                self.stat_evictions += 1
            self._note_mutation()

    def clear(self) -> None:
        with self._mu:
            self._store.clear()
            self._note_mutation()

    def __len__(self) -> int:
        with self._mu:
            return len(self._store)

    def __contains__(self, key) -> bool:
        with self._mu:
            return key in self._store

    def stats_snapshot(self) -> dict:
        with self._mu:
            return {
                "entries": len(self._store),
                "max_entries": self.max_entries,
                "hits": self.stat_hits,
                "misses": self.stat_misses,
                "evictions": self.stat_evictions,
            }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<NamedGlobal {self.name} entries={len(self)}>"


def named_global(name: str, max_entries: int = 256,
                 max_key_len: int = 0) -> NamedGlobal:
    """The registered-memo factory.  Idempotent per name (a module
    re-import gets the SAME store back — registry identity is the
    point); the first caller's bounds win."""
    with _named_globals_mu:
        g = _named_globals.get(name)
        if g is None:
            g = _named_globals[name] = NamedGlobal(
                name, max_entries=max_entries, max_key_len=max_key_len
            )
        return g


def named_globals() -> dict[str, NamedGlobal]:
    """Snapshot of the registry: the process's full inventory of
    sanctioned module-level mutable state (debug endpoints, tests)."""
    with _named_globals_mu:
        return dict(_named_globals)


def publish_global_stats(stats) -> None:
    """Fold every registered named-global's counters into a stats
    client as gauges tagged ``global:<name>`` — the /metrics handlers
    call this before rendering so memo behavior is scrapeable."""
    gs = named_globals()
    stats.gauge("analysis.globals.registered", len(gs))
    for name in sorted(gs):
        snap = gs[name].stats_snapshot()
        g_stats = stats.with_tags(f"global:{name}")
        g_stats.gauge("analysis.globals.entries", snap["entries"])
        g_stats.gauge("analysis.globals.hits", snap["hits"])
        g_stats.gauge("analysis.globals.misses", snap["misses"])
        g_stats.gauge("analysis.globals.evictions", snap["evictions"])


# -- blocking-call patches -------------------------------------------------


def _wrap_blocking(fn, kind):
    def wrapper(*a, **kw):
        s = _sched
        if s is not None:
            s.blocking_point(kind)
        _checker.note_blocking(kind)
        return fn(*a, **kw)

    wrapper.__lockcheck_orig__ = fn
    return wrapper


def _patch() -> None:
    global _patched
    if _patched:
        return
    _orig["os.fsync"] = os.fsync
    os.fsync = _wrap_blocking(os.fsync, "fsync")
    for meth in ("connect", "sendall", "send", "sendto", "recv", "recv_into", "accept"):
        attr = getattr(socket.socket, meth, None)
        if attr is None:  # pragma: no cover - platform variance
            continue
        _orig[f"socket.{meth}"] = attr
        setattr(socket.socket, meth, _wrap_blocking(attr, "socket"))
    _orig["subprocess.Popen.__init__"] = subprocess.Popen.__init__
    subprocess.Popen.__init__ = _wrap_blocking(
        subprocess.Popen.__init__, "subprocess"
    )
    _patched = True


def _unpatch() -> None:
    global _patched
    if not _patched:
        return
    os.fsync = _orig.pop("os.fsync")
    for meth in ("connect", "sendall", "send", "sendto", "recv", "recv_into", "accept"):
        orig = _orig.pop(f"socket.{meth}", None)
        if orig is not None:
            setattr(socket.socket, meth, orig)
    subprocess.Popen.__init__ = _orig.pop("subprocess.Popen.__init__")
    _patched = False


def sched_instrument() -> None:
    """Arm the seams an exploration run needs beyond the factories:
    guarded-class __setattr__ interception (field-write yield points)
    and the blocking-call patches.  Idempotent; shared with enable()."""
    _patch()
    for cls in _GUARDED_CLASSES:
        _patch_guarded_class(cls)


def sched_uninstrument() -> None:
    """Undo sched_instrument() UNLESS the full checker holds the same
    patches (enable() owns them then)."""
    if _enabled:
        return
    _unpatch()
    for cls in _GUARDED_CLASSES:
        _unpatch_guarded_class(cls)


# -- lifecycle -------------------------------------------------------------


def enable() -> None:
    """Turn the checker on for locks created FROM NOW ON (existing
    plain locks stay plain), patch the blocking-call probes, and
    instrument every registered guarded class's __setattr__."""
    global _enabled
    _enabled = True
    _patch()
    for cls in _GUARDED_CLASSES:
        _patch_guarded_class(cls)


def disable() -> None:
    global _enabled
    _enabled = False
    _unpatch()
    for cls in _GUARDED_CLASSES:
        _unpatch_guarded_class(cls)
    _checker.reset()


def reset() -> None:
    _checker.reset()


def take_violations() -> list[Violation]:
    return _checker.take_violations()


def check() -> None:
    """Raise LockCheckError if any violation was recorded since the
    last reset/take."""
    vs = _checker.take_violations()
    if vs:
        raise LockCheckError(
            f"{len(vs)} lock-discipline violation(s):\n\n"
            + "\n\n".join(v.describe() for v in vs)
        )


def _env_enabled() -> bool:
    return os.environ.get(ENV_VAR, "").lower() in ("1", "true", "yes")


if _env_enabled():  # subprocess workers inherit the env and self-enable
    enable()

    import atexit

    @atexit.register
    def _report_at_exit() -> None:  # pragma: no cover - subprocess path
        vs = _checker.take_violations()
        if vs:
            import sys

            print(
                f"[lockcheck] {len(vs)} violation(s) at exit:", file=sys.stderr
            )
            for v in vs:
                print(v.describe(), file=sys.stderr)
