"""Linter engine: file walking, suppressions, baseline, orchestration.

A Finding is identified across edits by a line-number-free FINGERPRINT
(rule + file + enclosing scope + normalized message + per-scope
occurrence index), so the checked-in baseline survives unrelated churn
above a grandfathered site.  The CLI (``python -m pilosa_tpu.analysis``)
exits nonzero only on findings whose fingerprint is not baselined.

Suppression: a comment ``# analysis-ok: <rule>: <reason>`` on the
finding's line or the line directly above silences that site; the
reason is mandatory (an empty reason is itself a finding).
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

RULES = (
    "lockstep-determinism",
    "lock-discipline",
    "stats-registry",
    "exception-hygiene",
    "deadline-propagation",
    "guarded-fields",
    "native-abi",
    "global-mutable-state",
    "check-then-act",
    "env-knob-outside-config",
    "stale-suppression",
)

# stale-suppression is engine-resident (it needs the post-suppression
# state of every other rule), not a rules.run_rule entry.
_ENGINE_RULES = ("stale-suppression",)

_SUPPRESS_RE = re.compile(r"#\s*analysis-ok:\s*([a-z-]+)\s*:\s*(.*)")


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    scope: str  # dotted enclosing def/class path, or "<module>"
    message: str
    suppressed: bool = False
    baselined: bool = False
    fingerprint: str = field(default="")

    def render(self) -> str:
        flag = " [baselined]" if self.baselined else (
            " [suppressed]" if self.suppressed else ""
        )
        return f"{self.rule}: {self.path}:{self.line} ({self.scope}) {self.message}{flag}"


@dataclass
class SourceFile:
    path: str  # absolute
    rel: str  # relative to scan root, forward slashes
    text: str
    tree: ast.AST
    # line -> (rule, reason) suppression comments
    suppressions: dict[int, tuple[str, str]]


def _scan_suppressions(text: str) -> dict[int, tuple[str, str]]:
    out: dict[int, tuple[str, str]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                out[tok.start[0]] = (m.group(1), m.group(2).strip())
    except tokenize.TokenError:  # pragma: no cover - unparseable file
        pass
    return out


def load_tree(root: str) -> list[SourceFile]:
    """Parse every .py file under ``root`` (the pilosa_tpu package)."""
    files: list[SourceFile] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            try:
                tree = ast.parse(text, filename=path)
            except SyntaxError:  # pragma: no cover - broken file
                continue
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            files.append(
                SourceFile(path, rel, text, tree, _scan_suppressions(text))
            )
    return files


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor tracking the dotted def/class scope path."""

    def __init__(self):
        self.scope: list[str] = []

    def scope_name(self) -> str:
        return ".".join(self.scope) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def apply_suppressions(findings: list[Finding], files: dict[str, SourceFile]) -> set:
    """Mark findings silenced by an ``analysis-ok`` comment on the same
    or the preceding line.  A matching comment with an EMPTY reason
    does not suppress (the reason is the point).  Returns the set of
    (path, comment line) suppressions that actually silenced something
    — the stale-suppression pass flags the rest."""
    used: set[tuple[str, int]] = set()
    for f in findings:
        sf = files.get(f.path)
        if sf is None:
            continue
        for line in (f.line, f.line - 1):
            sup = sf.suppressions.get(line)
            if sup and sup[0] == f.rule:
                # An empty-reason comment doesn't suppress, but it IS
                # attached to a live finding — stale-suppression must
                # not double-report what the empty reason already
                # surfaces as an unsuppressed finding.
                used.add((f.path, line))
                if sup[1]:
                    f.suppressed = True
                break
    return used


def stale_suppressions(
    files, used: set, active_rules: tuple
) -> list[Finding]:
    """Suppression comments whose rule fired nothing at their site: the
    tagged hazard was fixed or the code moved, and the rotting tag
    would silence the NEXT real finding there.  Only comments naming a
    rule in the active run are considered (a subset run must not call
    another rule's live tags stale); a comment naming an UNKNOWN rule
    is always a finding — it can never suppress anything."""
    out: list[Finding] = []
    for sf in files:
        for line, (rule, _reason) in sorted(sf.suppressions.items()):
            if rule == "stale-suppression":
                continue  # a meta-tag never fires "at" its own site
            known = rule in RULES
            if known and rule not in active_rules:
                continue
            if known and (sf.rel, line) in used:
                continue
            if known:
                msg = (
                    f"suppression `# analysis-ok: {rule}: ...` no longer "
                    "matches any finding at this site — the tagged hazard "
                    "was fixed or the code moved; delete the comment "
                    "(left in place it would silence the next real "
                    "finding here)"
                )
            else:
                msg = (
                    f"suppression names unknown rule `{rule}` — it can "
                    "never silence anything; fix the rule name or delete "
                    "the comment"
                )
            out.append(
                Finding("stale-suppression", sf.rel, line, "<suppression>", msg)
            )
    return out


def fingerprint_findings(findings: list[Finding]) -> None:
    """Stable ids: (rule, file, scope, normalized message) plus an
    occurrence index so N identical findings in one scope map to N
    distinct fingerprints (fixing one surfaces the regression if a
    new identical one appears)."""
    counts: dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        key = (f.rule, f.path, f.scope, f.message)
        idx = counts.get(key, 0)
        counts[key] = idx + 1
        raw = "|".join((f.rule, f.path, f.scope, f.message, str(idx)))
        f.fingerprint = hashlib.sha256(raw.encode()).hexdigest()[:16]


# -- baseline ---------------------------------------------------------------


def baseline_path(root: str) -> str:
    return os.path.join(root, "analysis", "baseline.json")


def load_baseline(path: str) -> dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return data.get("entries", {})


def write_baseline(path: str, findings: list[Finding]) -> None:
    entries = {
        f.fingerprint: {
            "rule": f.rule,
            "file": f.path,
            "scope": f.scope,
            "message": f.message,
        }
        for f in findings
        if not f.suppressed
    }
    doc = {
        "comment": (
            "Grandfathered pre-existing findings; python -m "
            "pilosa_tpu.analysis fails only on fingerprints not listed "
            "here. Regenerate with --write-baseline; prefer fixing or "
            "# analysis-ok: <rule>: <reason> suppressions over growing "
            "this file."
        ),
        "entries": dict(sorted(entries.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")


def apply_baseline(findings: list[Finding], entries: dict[str, dict]) -> None:
    for f in findings:
        if not f.suppressed and f.fingerprint in entries:
            f.baselined = True


# -- orchestration ----------------------------------------------------------


def package_root() -> str:
    """The installed pilosa_tpu package directory (the scan root)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_analysis(
    root: str | None = None,
    rules: tuple[str, ...] = RULES,
    baseline: str | None = None,
) -> list[Finding]:
    """Run the selected rules; returns ALL findings with suppressed /
    baselined flags applied.  New findings = neither flag set."""
    from pilosa_tpu.analysis import rules as rulemod

    root = root or package_root()
    files = load_tree(root)
    by_rel = {sf.rel: sf for sf in files}
    findings: list[Finding] = []
    for rule in rules:
        if rule in _ENGINE_RULES:
            continue
        findings.extend(rulemod.run_rule(rule, files, root))
    used = apply_suppressions(findings, by_rel)
    if "stale-suppression" in rules:
        stale = stale_suppressions(files, used, rules)
        # Stale-suppression findings are themselves suppressible (the
        # one legitimate case: a tag kept for a flapping, platform-
        # dependent rule) — run the normal pass over just them.
        apply_suppressions(stale, by_rel)
        findings.extend(stale)
    fingerprint_findings(findings)
    bpath = baseline or baseline_path(root)
    apply_baseline(findings, load_baseline(bpath))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def new_findings(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if not f.suppressed and not f.baselined]
