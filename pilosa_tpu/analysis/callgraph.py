"""Name-based intra-package call graph for reachability queries.

The lockstep-determinism rule needs "code reachable from the batch
execution entry points".  Python offers no cheap sound call graph, so
this is the standard project-linter over-approximation:

- every function/method in the package is a node keyed by
  (file, dotted scope);
- a call ``foo(...)`` / ``obj.foo(...)`` adds edges to every node whose
  BARE name is ``foo``, preferring same-file definitions when any
  exist (a same-file ``def foo`` almost always IS the callee);
- bare names on :data:`STOPLIST` (overwhelmingly stdlib/builtin method
  names — ``start``, ``get``, ``append`` ...) produce no edges, which
  keeps ``thread.start()`` from "reaching" ``Server.start`` and
  dragging the whole server into the reachable set.

Over-approximation errs toward MORE findings, which the suppression /
baseline machinery absorbs; the stoplist errs toward fewer, and is the
documented soundness hole (DEVELOPMENT.md).
"""

from __future__ import annotations

import ast

# Bare callee names never followed: stdlib/builtin collisions.
STOPLIST = frozenset(
    {
        "start", "join", "run", "close", "flush", "open", "read", "write",
        "append", "extend", "insert", "pop", "get", "put", "add", "remove",
        "discard", "clear", "copy", "update", "setdefault", "keys", "values",
        "items", "sort", "reverse", "index",
        "wait", "notify", "notify_all", "acquire", "release", "locked",
        "set", "is_set",
        "encode", "decode", "split", "rsplit", "strip", "lstrip", "rstrip",
        "lower", "upper", "replace", "format", "startswith", "endswith",
        "send", "recv", "sendall", "sendto", "recvfrom", "connect", "bind",
        "listen", "accept", "fileno", "seek", "tell", "truncate",
        "readline", "readinto", "makefile", "shutdown", "detach",
        "load", "loads", "dump", "dumps", "pack", "unpack", "unpack_from",
        "group", "match", "search", "sub", "findall", "finditer",
        "sleep", "exists", "abspath", "dirname", "basename", "relpath",
        "cancel", "total_seconds", "now", "utcnow",
    }
)


class _FuncInfo:
    __slots__ = ("key", "rel", "scope", "node", "bare", "calls")

    def __init__(self, rel: str, scope: str, node: ast.AST):
        self.rel = rel
        self.scope = scope
        self.key = (rel, scope)
        self.node = node
        self.bare = scope.rsplit(".", 1)[-1]
        self.calls: set[str] = set()  # bare callee names


def _callee_bare_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


class CallGraph:
    def __init__(self, files):
        # bare name -> [FuncInfo]
        self.by_bare: dict[str, list[_FuncInfo]] = {}
        self.funcs: dict[tuple, _FuncInfo] = {}
        for sf in files:
            self._index_file(sf)

    def _index_file(self, sf) -> None:
        rel = sf.rel

        class V(ast.NodeVisitor):
            def __init__(self):
                self.scope: list[str] = []
                self.stack: list[_FuncInfo] = []

            def visit_ClassDef(inner, node):
                inner.scope.append(node.name)
                inner.generic_visit(node)
                inner.scope.pop()

            def visit_FunctionDef(inner, node):
                inner.scope.append(node.name)
                info = _FuncInfo(rel, ".".join(inner.scope), node)
                self.funcs[info.key] = info
                self.by_bare.setdefault(info.bare, []).append(info)
                inner.stack.append(info)
                inner.generic_visit(node)
                inner.stack.pop()
                inner.scope.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(inner, node):
                # lambdas belong to the enclosing function's body
                inner.generic_visit(node)

            def visit_Call(inner, node):
                name = _callee_bare_name(node)
                if name and inner.stack:
                    inner.stack[-1].calls.add(name)
                inner.generic_visit(node)

        V().visit(sf.tree)

    def _resolve(self, caller: _FuncInfo, bare: str) -> list[_FuncInfo]:
        if bare in STOPLIST:
            return []
        cands = self.by_bare.get(bare, [])
        if not cands:
            return []
        same_file = [c for c in cands if c.rel == caller.rel]
        return same_file or cands

    def reachable_from(self, seeds) -> set[tuple]:
        """BFS over name edges from an iterable of (rel, scope) keys (or
        FuncInfo); returns the reachable set of keys, seeds included."""
        work = []
        for s in seeds:
            info = s if isinstance(s, _FuncInfo) else self.funcs.get(tuple(s))
            if info is not None:
                work.append(info)
        seen = {f.key for f in work}
        while work:
            cur = work.pop()
            for bare in cur.calls:
                for nxt in self._resolve(cur, bare):
                    if nxt.key not in seen:
                        seen.add(nxt.key)
                        work.append(nxt)
        return seen

    def seeds_matching(self, rel: str, prefix: str) -> list[_FuncInfo]:
        return [
            f
            for f in self.funcs.values()
            if f.rel == rel and f.bare.startswith(prefix)
        ]
