"""Executable replica write-protocol model + conformance checkers.

The router write protocol's contracts (PRs 7/9) live as prose in
CHANGES.md and docstrings: sequence assignment, majority-quorum commit,
abort tombstones only for provably-unapplied writes, applied-sequence
marks monotonic-max within a group epoch, WAL compaction floored at the
slowest tracked group (and at in-flight resync seeds), catch-up's
locked drain, resync's seed-seq handoff.  This module makes those
contracts EXECUTABLE, three ways:

1. **Small-scope exhaustive model checking** (:func:`model_check`):
   the protocol as an explicit state machine over G groups and up to W
   writes — writes with per-group apply/shed/ambiguous-failure
   outcomes, crash/restart with write-behind applied-mark persistence,
   in-order WAL replay, resync seeding, compaction, reads — explored
   breadth-first over EVERY reachable state, checking the invariants at
   each one:

   - no acked write lost: an acked sequence is applied by every group
     or still replayable from the log;
   - applied marks never regress within an epoch;
   - compaction never drops a record some live (tracked) group lacks;
   - a tombstoned write was never applied anywhere;
   - read-your-writes: a group serving reads holds every acked write.

   Small scope is the point (the classic small-scope hypothesis:
   protocol bugs show up at 2 groups x 2 writes); the whole space is a
   few thousand states and runs in tier-1.  ``break_*`` knobs mutate
   one rule at a time so tests can prove each invariant actually
   trips when its protecting rule is removed.

2. **Trace conformance** (:func:`check_trace`): the real router / WAL /
   catch-up / resync emit event records at their protocol transitions
   (:func:`emit` — one ``is None`` test when no collector is installed,
   zero cost in production) and the checker validates a recorded event
   stream against the same invariants.  Runs under the interleaving
   explorer's scenarios (analysis/sched.py) and, via the conftest
   gate, under the fault-seam replica e2e tests.  Traces are grouped
   by ``src`` (the WAL object identity == one sequence space == one
   router incarnation); events for sequences that predate the
   collector are tolerated (a recovered WAL replays records this trace
   never saw appended).

3. **Linearizability checking** (:func:`check_linearizable` over a
   :class:`LinHistory`): explored histories of Fragment
   set/clear/count and qcache store/invalidate are checked against
   their sequential specs with the Wing & Gong search (small histories
   only — the explorer's scenarios produce a handful of operations).
"""

from __future__ import annotations

from typing import Callable, Optional

# -- event collection --------------------------------------------------------
#
# The collector is a plain list; list.append is atomic under the GIL,
# so emission needs no lock and the event order IS the observation
# order.  (A free-threaded build would need an explicit lock here —
# noted in DEVELOPMENT.md next to the other GIL-era assumptions.)

_collector: Optional[list] = None


def emit(kind: str, **fields) -> None:
    """Record one protocol event when a collector is installed; a
    single None test otherwise (the zero-cost-off contract)."""
    c = _collector
    if c is not None:
        c.append((kind, fields))


def install_collector() -> list:
    """Install and return a fresh event list (tests / explorer)."""
    global _collector
    _collector = []
    return _collector


def uninstall_collector() -> None:
    global _collector
    _collector = None


def collector_installed() -> bool:
    return _collector is not None


# -- trace conformance -------------------------------------------------------


class _TraceState:
    """Per-src (per WAL / per router incarnation) running state."""

    __slots__ = ("last_append", "appended", "aborted", "ok_applies",
                 "acked_max", "marks", "quorum", "plan_floor")

    def __init__(self):
        self.last_append = 0
        self.appended: set[int] = set()
        self.aborted: set[int] = set()
        self.ok_applies: dict[int, set] = {}  # seq -> group names (2xx)
        self.acked_max = 0  # highest 2xx-acked sequence so far
        self.marks: dict[str, tuple] = {}  # group -> (epoch, value)
        self.quorum: Optional[int] = None
        self.plan_floor: Optional[int] = None


def check_trace(events: list) -> list[str]:
    """Validate an emitted event stream against the protocol model.
    Returns human-readable violation strings (empty = conformant)."""
    by_src: dict = {}
    out: list[str] = []

    def st(fields) -> _TraceState:
        return by_src.setdefault(fields.get("src"), _TraceState())

    for kind, f in events:
        s = st(f)
        if kind == "config":
            s.quorum = f.get("quorum")
        elif kind == "append":
            seq = f["seq"]
            if seq <= s.last_append:
                out.append(
                    f"append seq {seq} not strictly increasing "
                    f"(last was {s.last_append})"
                )
            s.last_append = max(s.last_append, seq)
            s.appended.add(seq)
        elif kind == "abort":
            seq = f["seq"]
            if s.ok_applies.get(seq):
                out.append(
                    f"abort tombstoned seq {seq} which group(s) "
                    f"{sorted(s.ok_applies[seq])} already applied — replay "
                    "will never deliver a write a live group holds"
                )
            s.aborted.add(seq)
        elif kind == "apply":
            seq = f["seq"]
            if seq in s.aborted:
                out.append(
                    f"group {f.get('group')} applied seq {seq} AFTER its "
                    "abort tombstone — replay delivered a tombstoned write"
                )
            if f.get("ok"):
                s.ok_applies.setdefault(seq, set()).add(f.get("group"))
        elif kind == "ack":
            seq, status = f["seq"], f["status"]
            if status < 300:
                if seq in s.aborted:
                    out.append(f"acked 2xx for aborted seq {seq}")
                applied = f.get("applied", 0)
                if s.quorum is not None and applied < s.quorum:
                    out.append(
                        f"seq {seq} acked 2xx with {applied} applies "
                        f"< quorum {s.quorum}"
                    )
                s.acked_max = max(s.acked_max, seq)
        elif kind in ("mark", "probe_mark", "seed"):
            g = f.get("group")
            epoch = f.get("epoch")
            value = f.get("value", f.get("seq", 0))
            prev = s.marks.get(g)
            if (
                prev is not None
                and prev[0] is not None
                and epoch is not None
                and prev[0] == epoch
                and value < prev[1]
            ):
                out.append(
                    f"group {g} applied mark regressed {prev[1]} -> {value} "
                    f"within epoch {epoch} ({kind})"
                )
            if prev is not None and prev[0] == epoch:
                value = max(value, prev[1])
            s.marks[g] = (epoch, value)
        elif kind == "compact_plan":
            floor = f["floor"]
            tracked = f.get("tracked", {})
            floors = f.get("floors", [])
            lo = min(list(tracked.values()) + list(floors), default=None)
            if lo is not None and floor > lo:
                lag = [g for g, a in tracked.items() if a < floor]
                out.append(
                    f"compaction floor {floor} exceeds the minimum tracked "
                    f"applied mark {lo} (lagging: {sorted(lag)}, resync "
                    f"floors: {sorted(floors)}) — dropped records a live "
                    "group still needs"
                )
            s.plan_floor = floor
        elif kind == "wal_compact":
            floor = f["floor"]
            if s.plan_floor is not None and floor > s.plan_floor:
                out.append(
                    f"WAL compacted past the planned floor "
                    f"({floor} > {s.plan_floor})"
                )
        elif kind == "read":
            applied = f.get("applied", 0)
            if applied < s.acked_max:
                out.append(
                    f"read routed to group {f.get('group')} at applied mark "
                    f"{applied} < acked head {s.acked_max} — read-your-writes "
                    "broken"
                )
        elif kind == "reshard":
            # Ownership-epoch fence events (src = the router).  The map
            # epoch must strictly increase — a flip that reuses an epoch
            # lets a group accept a stale ownership view as current.
            epoch = f.get("epoch")
            prev = s.marks.get("__map_epoch__")
            if prev is not None and epoch is not None and epoch <= prev[1]:
                out.append(
                    f"reshard map epoch did not advance "
                    f"({prev[1]} -> {epoch})"
                )
            s.marks["__map_epoch__"] = (None, epoch)
    return out


# -- small-scope exhaustive protocol model -----------------------------------
#
# State encoding (hashable tuples only):
#   next_seq       int — the next sequence the router would assign
#   records        tuple of (seq, live: bool) for sequences still in the
#                  log; compaction removes entries entirely
#   acked          tuple of acked (committed 2xx) sequences
#   groups         tuple per group of (data, mark, persisted, epoch, rot)
#   floor          compaction floor already applied (highest dropped seq)
#
# Two watermarks per group, deliberately distinct: ``data`` is the
# highest write whose BITS the group durably holds (fragment state —
# survives restart), ``mark`` is its AppliedSeq counter (write-behind
# persistence: restart falls back to ``persisted`` and replay
# re-delivers the suffix the group already holds — the documented
# harmless undercount).  mark <= data always; fan-out, replay, and
# seeding are in-order, so "group g holds write s" == data_g >= s
# (matching the real protocol; see catchup.py).

OUT_APPLY, OUT_SHED, OUT_FAIL = "apply", "shed", "fail"


class ModelViolation(Exception):
    pass


class ModelResult:
    __slots__ = ("states", "transitions", "violations")

    def __init__(self):
        self.states = 0
        self.transitions = 0
        self.violations: list[str] = []

    @property
    def ok(self) -> bool:
        return not self.violations


def model_check(
    n_groups: int = 2,
    max_writes: int = 2,
    max_restarts: int = 1,
    break_quorum: bool = False,
    break_compaction: bool = False,
    break_abort: bool = False,
    max_states: int = 200_000,
) -> ModelResult:
    """Exhaustively explore the protocol state machine and check the
    invariants at every reachable state.

    ``break_quorum`` commits on ANY single apply AND leaves groups
    that missed the write in the read rotation (the PR 6 review's
    shed-was-ACKed hazard: a loaded group sheds a write its sibling
    commits, then keeps serving stale reads); ``break_compaction``
    computes the floor over in-rotation groups only (dropping what a
    demoted laggard still needs — the seeded compaction bug);
    ``break_abort`` tombstones any write that answered fewer than
    quorum (aborting writes a group applied).  Each knob must produce
    violations — tests assert that — while the unbroken model explores
    clean."""
    quorum = 1 if break_quorum else (n_groups // 2 + 1)
    res = ModelResult()
    init = (
        1,  # next_seq
        (),  # records
        (),  # acked
        tuple((0, 0, 0, 0, True) for _ in range(n_groups)),
        0,  # floor
        0,  # restarts used
    )
    seen = {init}
    work = [init]

    def invariants(state) -> None:
        next_seq, records, acked, groups, floor, _r = state
        live = {s for s, alive in records if alive}
        for s in acked:
            for gi, (data, _m, _p, _e, _rot) in enumerate(groups):
                if data < s and s not in live:
                    res.violations.append(
                        f"acked write {s} lost: group {gi} holds data up to "
                        f"{data} and the record is no longer replayable "
                        f"(state {state})"
                    )
                    return

    def out_state(state):
        if state not in seen:
            seen.add(state)
            invariants(state)
            work.append(state)
        res.transitions += 1

    def write_outcomes(n):
        # Every per-group outcome vector for the in-rotation groups.
        if n == 0:
            yield ()
            return
        for rest in write_outcomes(n - 1):
            for o in (OUT_APPLY, OUT_SHED, OUT_FAIL):
                yield (o,) + rest

    while work:
        if res.states >= max_states:
            res.violations.append("state-space cap exceeded")
            break
        state = work.pop()
        res.states += 1
        if res.violations:
            break
        next_seq, records, acked, groups, floor, restarts = state
        in_rot = [i for i, g in enumerate(groups) if g[4]]
        live_seqs = sorted(s for s, alive in records if alive)

        # WRITE: quorum precondition, then every outcome vector.
        if len(in_rot) >= quorum and next_seq <= max_writes:
            for outs in write_outcomes(len(in_rot)):
                seq = next_seq
                applied_ct = sum(1 for o in outs if o == OUT_APPLY)
                shed_any = any(o == OUT_SHED for o in outs)
                ambiguous = any(o == OUT_FAIL for o in outs)
                gl = list(groups)
                for pos, gi in enumerate(in_rot):
                    d, m, p, e, _rot = gl[gi]
                    if outs[pos] == OUT_APPLY:
                        gl[gi] = (max(d, seq), max(m, seq), p, e, True)
                    else:
                        # A group that missed a sequenced write leaves
                        # the rotation until replay re-converges it —
                        # UNLESS the broken-quorum variant models the
                        # shed-was-ACKed hazard (no demotion).
                        gl[gi] = (d, m, p, e, bool(break_quorum))
                recs = records + ((seq, True),)
                new_acked = acked
                tombstoned = False
                if applied_ct >= quorum:
                    new_acked = acked + (seq,)
                elif applied_ct == 0 and shed_any and not ambiguous:
                    # Provably applied nowhere: tombstone.
                    recs = records + ((seq, False),)
                    tombstoned = True
                elif break_abort and applied_ct < quorum:
                    recs = records + ((seq, False),)
                    tombstoned = True
                if tombstoned and applied_ct > 0:
                    res.violations.append(
                        f"write {seq} tombstoned with {applied_ct} group(s) "
                        "having applied it — replay will never re-deliver a "
                        f"write a live group holds (state {state})"
                    )
                out_state((seq + 1, recs, new_acked, tuple(gl), floor,
                           restarts))

        # PERSIST: write-behind applied-mark persistence per group.
        for gi, (d, m, p, e, rot) in enumerate(groups):
            if p != m:
                gl = list(groups)
                gl[gi] = (d, m, m, e, rot)
                out_state((next_seq, records, acked, tuple(gl), floor,
                           restarts))

        # RESTART: epoch bump; the counter falls back to its persisted
        # value (write-behind undercount) but the DATA survives; out of
        # rotation until replay re-converges the counter.
        if restarts < max_restarts:
            for gi, (d, m, p, e, rot) in enumerate(groups):
                gl = list(groups)
                gl[gi] = (d, p, p, e + 1, False)
                out_state((next_seq, records, acked, tuple(gl), floor,
                           restarts + 1))

        # REPLAY: in-order delivery of the next LIVE record past the
        # counter (idempotent for records the data already holds); a
        # group with nothing left to replay rejoins the rotation
        # (tombstones are never delivered — replay skips them).
        for gi, (d, m, p, e, rot) in enumerate(groups):
            if rot:
                continue
            missing = [s for s in live_seqs if s > m]
            if m < floor and not missing:
                # Everything past its counter was compacted away: only
                # a resync seed can bring it back (modeled below).
                continue
            if missing:
                s0 = missing[0]
                gl = list(groups)
                gl[gi] = (max(d, s0), s0, p, e, False)
            else:
                gl = list(groups)
                gl[gi] = (d, m, p, e, True)
            out_state((next_seq, records, acked, tuple(gl), floor,
                       restarts))

        # SEED (resync handoff): the laggard becomes byte-identical to
        # the best in-rotation donor and adopts its counter; the
        # remaining suffix replays normally.
        if in_rot:
            donor = max(in_rot, key=lambda i: groups[i][1])
            dd, dm = groups[donor][0], groups[donor][1]
            for gi, (d, m, p, e, rot) in enumerate(groups):
                if not rot and m < dm:
                    gl = list(groups)
                    gl[gi] = (max(d, dd), dm, dm, e, False)
                    out_state((next_seq, records, acked, tuple(gl), floor,
                               restarts))

        # COMPACT: floor at the minimum applied counter over TRACKED
        # groups (all of them — a demoted laggard still replays), or —
        # broken variant — over the in-rotation groups only.
        tracked = in_rot if break_compaction else range(len(groups))
        marks = [groups[i][1] for i in tracked]
        if marks:
            new_floor = min(marks)
            if new_floor > floor:
                recs = tuple(
                    (s, alive) for s, alive in records if s > new_floor
                )
                out_state((next_seq, recs, acked, groups, new_floor,
                           restarts))

        # READ: route to any in-rotation group; read-your-writes check
        # against the data the group actually serves.
        for gi in in_rot:
            data = groups[gi][0]
            missed = [s for s in acked if s > data]
            if missed:
                res.violations.append(
                    f"read-your-writes: group {gi} serves reads holding data "
                    f"up to {data} but write(s) {missed} are acked "
                    f"(state {state})"
                )
    return res


# -- sharded (2-D slice-shard x replica) protocol model ----------------------
#
# PR 17 promotes the router to a (slice-shard x replica) layout: each
# shard owns a contiguous slice range, sequences its writes in its OWN
# sequence space (its own WAL, its own sequencer lock), and runs the
# PR 7/9 catch-up/resync/compaction machinery per shard unchanged.  The
# sharded model is the PRODUCT of S per-shard instances of the
# :func:`model_check` machine — same per-shard transitions and
# invariants — plus the two properties that only exist ACROSS shards:
#
# - **exclusive ownership**: a write routed to shard k lands on shard
#   k's groups only.  Unconstrained reads fan to every shard and merge
#   by sum/union, so a write applied on a non-owning shard is counted
#   twice (``break_routing`` plants exactly that bug: the foreign-data
#   invariant must trip);
# - **cross-shard read-your-writes**: a merged read picks one
#   in-rotation group per shard; every shard's acked writes must be
#   visible in the group IT contributed (per-shard read-your-writes
#   composes — the model checks the composition explicitly at every
#   state).
#
# Scope stays small on purpose: 2 shards x 2 replicas x 1 write per
# shard x 1 shared restart explores in well under a second; the
# per-shard machinery is already exercised at 2 writes by
# :func:`model_check`, so the product run only needs enough writes to
# give every shard a sequence space of its own.


def model_check_sharded(
    n_shards: int = 2,
    n_groups: int = 2,
    max_writes_per_shard: int = 1,
    max_restarts: int = 1,
    break_quorum: bool = False,
    break_compaction: bool = False,
    break_abort: bool = False,
    break_routing: bool = False,
    max_states: int = 400_000,
) -> ModelResult:
    """Exhaustively explore the sharded protocol: ``n_shards``
    independent sequence spaces of ``n_groups`` replicas each, a shared
    restart budget, per-shard invariants at every state plus the
    cross-shard exclusive-ownership and merged-read checks.

    ``break_quorum`` / ``break_compaction`` / ``break_abort`` mutate
    the same per-shard rules as :func:`model_check` (applied to shard
    0's instance — one broken shard must be enough to trip).
    ``break_routing`` mis-routes shard 0's writes onto shard 1's groups
    too, modeling a router that fans a bit-write across shards — the
    double-count hazard the slice-cover routing exists to prevent."""
    quorum = 1 if break_quorum else (n_groups // 2 + 1)
    res = ModelResult()
    # Per-shard sub-state mirrors model_check: (next_seq, records,
    # acked, groups, floor).  ``foreign`` is a per-shard tuple of
    # per-group highest FOREIGN sequence applied (data the shard does
    # not own — always 0 unless break_routing plants it).
    shard0 = (
        1,
        (),
        (),
        tuple((0, 0, 0, 0, True) for _ in range(n_groups)),
        0,
    )
    init = (
        tuple(shard0 for _ in range(n_shards)),
        tuple(tuple(0 for _ in range(n_groups)) for _ in range(n_shards)),
        0,  # shared restarts used
    )
    seen = {init}
    work = [init]

    def invariants(state) -> None:
        shards, foreign, _r = state
        for si, (next_seq, records, acked, groups, floor) in enumerate(shards):
            live = {s for s, alive in records if alive}
            for s in acked:
                for gi, (data, _m, _p, _e, _rot) in enumerate(groups):
                    if data < s and s not in live:
                        res.violations.append(
                            f"shard {si}: acked write {s} lost: group {gi} "
                            f"holds data up to {data} and the record is no "
                            f"longer replayable (state {state})"
                        )
                        return
        for si, per_group in enumerate(foreign):
            for gi, fseq in enumerate(per_group):
                if fseq:
                    res.violations.append(
                        f"shard {si} group {gi} holds foreign write {fseq} "
                        "for a slice range it does not own — an "
                        "unconstrained fan-out read double-counts it "
                        f"(state {state})"
                    )
                    return

    def out_state(state):
        if state not in seen:
            seen.add(state)
            invariants(state)
            work.append(state)
        res.transitions += 1

    def write_outcomes(n):
        if n == 0:
            yield ()
            return
        for rest in write_outcomes(n - 1):
            for o in (OUT_APPLY, OUT_SHED, OUT_FAIL):
                yield (o,) + rest

    while work:
        if res.states >= max_states:
            res.violations.append("state-space cap exceeded")
            break
        state = work.pop()
        res.states += 1
        if res.violations:
            break
        shards, foreign, restarts = state

        def sub(si, new_shard, new_foreign=None):
            sl = list(shards)
            sl[si] = new_shard
            fl = list(foreign) if new_foreign is None else new_foreign
            out_state((tuple(sl), tuple(fl), restarts))

        for si, (next_seq, records, acked, groups, floor) in enumerate(shards):
            in_rot = [i for i, g in enumerate(groups) if g[4]]
            live_seqs = sorted(s for s, alive in records if alive)
            # The break_* knobs target shard 0's instance only.
            b_quorum = break_quorum and si == 0
            b_compaction = break_compaction and si == 0
            b_abort = break_abort and si == 0
            s_quorum = 1 if b_quorum else (n_groups // 2 + 1)

            # WRITE in shard si's sequence space.
            if len(in_rot) >= s_quorum and next_seq <= max_writes_per_shard:
                for outs in write_outcomes(len(in_rot)):
                    seq = next_seq
                    applied_ct = sum(1 for o in outs if o == OUT_APPLY)
                    shed_any = any(o == OUT_SHED for o in outs)
                    ambiguous = any(o == OUT_FAIL for o in outs)
                    gl = list(groups)
                    for pos, gi in enumerate(in_rot):
                        d, m, p, e, _rot = gl[gi]
                        if outs[pos] == OUT_APPLY:
                            gl[gi] = (max(d, seq), max(m, seq), p, e, True)
                        else:
                            gl[gi] = (d, m, p, e, bool(b_quorum))
                    recs = records + ((seq, True),)
                    new_acked = acked
                    tombstoned = False
                    if applied_ct >= s_quorum:
                        new_acked = acked + (seq,)
                    elif applied_ct == 0 and shed_any and not ambiguous:
                        recs = records + ((seq, False),)
                        tombstoned = True
                    elif b_abort and applied_ct < s_quorum:
                        recs = records + ((seq, False),)
                        tombstoned = True
                    if tombstoned and applied_ct > 0:
                        res.violations.append(
                            f"shard {si}: write {seq} tombstoned with "
                            f"{applied_ct} group(s) having applied it "
                            f"(state {state})"
                        )
                    fl = list(foreign)
                    if break_routing and si == 0 and applied_ct >= s_quorum:
                        # Mis-route: the acked write also lands on every
                        # other shard's groups as foreign data.
                        for oi in range(n_shards):
                            if oi != si:
                                fl[oi] = tuple(
                                    max(f, seq) for f in fl[oi]
                                )
                    sub(si, (seq + 1, recs, new_acked, tuple(gl), floor), fl)

            # PERSIST / RESTART / REPLAY / SEED / COMPACT per shard.
            for gi, (d, m, p, e, rot) in enumerate(groups):
                if p != m:
                    gl = list(groups)
                    gl[gi] = (d, m, m, e, rot)
                    sub(si, (next_seq, records, acked, tuple(gl), floor))
            if restarts < max_restarts:
                for gi, (d, m, p, e, rot) in enumerate(groups):
                    gl = list(groups)
                    gl[gi] = (d, p, p, e + 1, False)
                    sl = list(shards)
                    sl[si] = (next_seq, records, acked, tuple(gl), floor)
                    out_state((tuple(sl), foreign, restarts + 1))
            for gi, (d, m, p, e, rot) in enumerate(groups):
                if rot:
                    continue
                missing = [s for s in live_seqs if s > m]
                if m < floor and not missing:
                    continue
                if missing:
                    s0 = missing[0]
                    gl = list(groups)
                    gl[gi] = (max(d, s0), s0, p, e, False)
                else:
                    gl = list(groups)
                    gl[gi] = (d, m, p, e, True)
                sub(si, (next_seq, records, acked, tuple(gl), floor))
            if in_rot:
                donor = max(in_rot, key=lambda i: groups[i][1])
                dd, dm = groups[donor][0], groups[donor][1]
                for gi, (d, m, p, e, rot) in enumerate(groups):
                    if not rot and m < dm:
                        gl = list(groups)
                        gl[gi] = (max(d, dd), dm, dm, e, False)
                        sub(si, (next_seq, records, acked, tuple(gl), floor))
            tracked = in_rot if b_compaction else range(len(groups))
            marks = [groups[i][1] for i in tracked]
            if marks:
                new_floor = min(marks)
                if new_floor > floor:
                    recs = tuple(
                        (s, alive) for s, alive in records if s > new_floor
                    )
                    sub(si, (next_seq, recs, acked, groups, new_floor))

        # MERGED READ: one in-rotation group per shard (every
        # combination); shard k's acked writes must be visible in the
        # group shard k contributed — the cross-shard composition of
        # read-your-writes that the fan-out merge relies on.
        picks = [
            [i for i, g in enumerate(sh[3]) if g[4]] for sh in shards
        ]
        if all(picks):
            for si, choices in enumerate(picks):
                _ns, _recs, acked, groups, _fl = shards[si]
                for gi in choices:
                    data = groups[gi][0]
                    missed = [s for s in acked if s > data]
                    if missed:
                        res.violations.append(
                            f"merged read: shard {si} contributed group "
                            f"{gi} holding data up to {data} but write(s) "
                            f"{missed} are acked on that shard "
                            f"(state {state})"
                        )
    return res


# -- live-reshard (split -> stream -> epoch-fenced flip) model ---------------
#
# Resharding splits one shard's slice range and hands the upper half to
# a new replica set with ZERO failed writes: fragments stream to the
# new owners while the OLD shard keeps serving, then an epoch fence
# blocks the moved range just long enough to stream the delta and flip
# ownership.  The model abstracts writes to the moved range as opaque
# ids (the per-shard sequence machinery is checked by
# :func:`model_check_sharded`); what it explores is the ORDER of
# stream / flip / clear against concurrent writes:
#
# - flip only after every new-owner group holds all acked moved-range
#   writes (``break_fence`` flips without the precondition — the
#   read-your-writes invariant must trip);
# - the old owner's moved-range fragments are cleared only AFTER the
#   flip (``break_clear`` clears early — acked data is lost while the
#   old shard still owns the range).


def model_check_reshard(
    max_writes: int = 2,
    break_fence: bool = False,
    break_clear: bool = False,
    max_states: int = 50_000,
) -> ModelResult:
    """Explore the split -> stream -> epoch-fenced flip protocol for
    one moved slice range, two groups per shard."""
    res = ModelResult()
    # State: (owner, next_id, acked, old0, old1, new0, new1, epoch)
    # where acked/old*/new* are frozensets of moved-range write ids.
    empty = frozenset()
    init = (0, 1, empty, empty, empty, empty, empty, 0)
    seen = {init}
    work = [init]

    def invariants(state) -> None:
        owner, _n, acked, old0, old1, new0, new1, _e = state
        serving = (old0, old1) if owner == 0 else (new0, new1)
        for gi, data in enumerate(serving):
            missed = sorted(acked - data)
            if missed:
                res.violations.append(
                    f"moved-range read: owning shard {owner} group {gi} "
                    f"is missing acked write(s) {missed} (state {state})"
                )
                return

    def out_state(state):
        if state not in seen:
            seen.add(state)
            invariants(state)
            work.append(state)
        res.transitions += 1

    while work:
        if res.states >= max_states:
            res.violations.append("state-space cap exceeded")
            break
        state = work.pop()
        res.states += 1
        if res.violations:
            break
        owner, next_id, acked, old0, old1, new0, new1, epoch = state

        # WRITE to the moved range: applies on the CURRENT owner's
        # groups (both — quorum behavior is model_check_sharded's job),
        # never fails (the fence holds writes, it does not fail them).
        if next_id <= max_writes:
            w = frozenset({next_id})
            if owner == 0:
                out_state((0, next_id + 1, acked | w, old0 | w, old1 | w,
                           new0, new1, epoch))
            else:
                out_state((1, next_id + 1, acked | w, old0, old1,
                           new0 | w, new1 | w, epoch))

        if owner == 0:
            # STREAM: one new-owner group copies a donor old-owner
            # group's current moved-range bytes (each group streams
            # independently; repeated rounds pick up the delta).
            for donor in (old0, old1):
                out_state((0, next_id, acked, old0, old1,
                           new0 | donor, new1, epoch))
                out_state((0, next_id, acked, old0, old1,
                           new0, new1 | donor, epoch))
            # FLIP: behind the fence — every new-owner group must hold
            # all acked moved-range writes first (break_fence skips the
            # precondition: the invariant must trip on the next read).
            if break_fence or (acked <= new0 and acked <= new1):
                out_state((1, next_id, acked, old0, old1,
                           new0, new1, epoch + 1))
            if break_clear:
                # Premature clear: the old owner drops the moved range
                # while it still owns it.
                out_state((0, next_id, acked, empty, empty,
                           new0, new1, epoch))
        else:
            # CLEAR: after the flip the old owner reclaims the moved
            # fragments — safe, it no longer serves the range.
            out_state((1, next_id, acked, empty, empty,
                       new0, new1, epoch))
    return res


# -- linearizability ---------------------------------------------------------


class LinHistory:
    """Concurrent operation history recorded by scenario threads.

    ``invoke``/``respond`` use list appends (GIL-atomic) so recording
    adds no locks — under the explorer only one thread runs at a time
    anyway, and the global append order is the real-time order the
    checker respects."""

    def __init__(self):
        self._tick = [0]
        self.ops: list[dict] = []

    def invoke(self, tid: int, op, args=()) -> int:
        opid = len(self.ops)
        self.ops.append({
            "tid": tid, "op": op, "args": args,
            "inv": self._next(), "res": None, "result": None,
        })
        return opid

    def respond(self, opid: int, result) -> None:
        rec = self.ops[opid]
        rec["res"] = self._next()
        rec["result"] = result

    def _next(self) -> int:
        self._tick[0] += 1
        return self._tick[0]


def check_linearizable(history: LinHistory, init_state,
                       apply: Callable) -> tuple[bool, str]:
    """Wing & Gong search: is there a sequential order of the completed
    operations, consistent with real-time order, that the sequential
    spec accepts?  ``apply(state, op, args)`` returns either one
    ``(new_state, result)`` or a LIST of candidates (a nondeterministic
    spec — e.g. a cache that may conservatively decline a store).
    States must be hashable.  Returns (ok, detail)."""
    ops = [o for o in history.ops if o["res"] is not None]
    n = len(ops)
    seen: set = set()

    def dfs(done_mask: int, state) -> bool:
        if done_mask == (1 << n) - 1:
            return True
        key = (done_mask, state)
        if key in seen:
            return False
        seen.add(key)
        # An op may linearize next only if its invocation precedes the
        # earliest response among the other not-yet-linearized ops.
        first_res = min(
            (ops[i]["res"] for i in range(n) if not done_mask & (1 << i)),
        )
        for i in range(n):
            if done_mask & (1 << i):
                continue
            if ops[i]["inv"] > first_res:
                continue
            outs = apply(state, ops[i]["op"], ops[i]["args"])
            if isinstance(outs, tuple):
                outs = [outs]
            for new_state, result in outs:
                if result != ops[i]["result"]:
                    continue
                if dfs(done_mask | (1 << i), new_state):
                    return True
        return False

    if dfs(0, init_state):
        return True, ""
    rendered = "; ".join(
        f"t{o['tid']}:{o['op']}{o['args']}->{o['result']}" for o in ops
    )
    return False, f"no linearization of [{rendered}]"


# -- sequential specs for the explored histories -----------------------------


def bitmap_apply(state, op, args):
    """Sequential spec for Fragment set/clear/count at (row, col)
    granularity: state = frozenset of set (row, col) pairs."""
    if op == "set":
        changed = args not in state
        return (state | {args}) if changed else state, changed
    if op == "clear":
        changed = args in state
        return (state - {args}) if changed else state, changed
    if op == "count":
        return state, len(state)
    raise ValueError(op)


def qcache_apply(state, op, args):
    """Sequential spec for the generation-validated cache: state =
    (stored entry or None, current generation).  ``store`` may succeed
    ONLY while its snapshot generation is still current — but it may
    always DECLINE (the real cache's vector re-check is conservative:
    refusing a store is safe, stamping a stale one is not), so the spec
    is nondeterministic on the False branch.  ``bump`` is a write
    (generation advance); ``get`` returns the stored value only while
    its generation is current."""
    stored, gen = state
    if op == "store":
        value, snap_gen = args
        outs = [(state, False)]  # declining is always legal
        if snap_gen == gen:
            outs.append((((value, gen), gen), True))
        return outs
    if op == "bump":
        return (stored, gen + 1), None
    if op == "get":
        if stored is not None and stored[1] == gen:
            return state, stored[0]
        return state, None
    raise ValueError(op)
