"""CLI: ``python -m pilosa_tpu.analysis`` — the CI gate.

Exit 0 when every finding is suppressed or baselined; exit 1 on NEW
findings (and print them).  ``--write-baseline`` grandfathers the
current findings; ``--write-registry`` regenerates the counters
registry (COUNTERS.md); ``--all`` lists every finding including the
grandfathered ones.

The interleaving-explorer lane (analysis/sched.py + spec.py):

- ``--explore`` lists the scenario registry;
- ``--explore <name|all>`` exhaustively explores one scenario (or the
  whole live suite plus the protocol model check) under the preemption
  bound (``--bound``) — exit 1 with printed schedule strings on any
  violation;
- ``--explore <name> --schedule <string>`` replays one serialized
  schedule (the deterministic repro for a failure CI printed).
"""

from __future__ import annotations

import argparse
import sys

from pilosa_tpu.analysis import engine, registry


def _run_explore(name, schedule, bound) -> int:
    from pilosa_tpu.analysis import sched, scenarios, spec

    if not name:
        print("explorer scenarios (see DEVELOPMENT.md):")
        for sname, s in sorted(scenarios.SCENARIOS.items()):
            tag = " [known-bug fixture]" if s.known_bug else ""
            print(f"  {sname}{tag}")
            if s.description:
                first = s.description.strip().splitlines()[0].strip()
                print(f"      {first}")
        return 0

    if schedule:
        s = scenarios.get(name)
        outcomes = sched.replay(s, schedule)
        for o in outcomes:
            print(o.describe())
        if outcomes:
            return 1
        print(f"{name}: schedule {schedule} replayed clean")
        return 0

    targets = (
        scenarios.live_scenarios() if name == "all" else [scenarios.get(name)]
    )
    rc = 0
    for s in targets:
        res = sched.explore(s, bound=bound)
        print(res.describe())
        if not res.ok:
            rc = 1
    if name == "all":
        model = spec.model_check(n_groups=3, max_writes=2)
        print(
            f"replica-protocol model: {model.states} states explored, "
            f"{len(model.violations)} violation(s)"
        )
        for v in model.violations:
            print("  " + v)
        if not model.ok:
            rc = 1
    if rc:
        print(
            "replay a failing schedule with: python -m pilosa_tpu.analysis "
            "--explore <scenario> --schedule <string>",
            file=sys.stderr,
        )
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m pilosa_tpu.analysis",
        description="Project invariant linter (see DEVELOPMENT.md).",
    )
    p.add_argument("--root", default=None, help="package dir to scan (default: installed pilosa_tpu)")
    p.add_argument("--rules", default=None, help="comma-separated subset of: " + ",".join(engine.RULES))
    p.add_argument("--baseline", default=None, help="baseline file (default: <root>/analysis/baseline.json)")
    p.add_argument("--write-baseline", action="store_true", help="grandfather the current findings and exit")
    p.add_argument("--write-registry", action="store_true", help="regenerate analysis/COUNTERS.md and exit")
    p.add_argument("--all", action="store_true", help="also list suppressed/baselined findings")
    p.add_argument("--explore", nargs="?", const="", default=None,
                   metavar="SCENARIO",
                   help="interleaving explorer: list scenarios (no value), "
                        "run one, or `all` for the live suite + model check")
    p.add_argument("--schedule", default=None,
                   help="with --explore <scenario>: replay this serialized "
                        "schedule string")
    p.add_argument("--bound", type=int, default=None,
                   help="preemption bound for --explore (default: per-scenario)")
    args = p.parse_args(argv)

    root = args.root or engine.package_root()

    if args.explore is not None:
        return _run_explore(args.explore, args.schedule, args.bound)

    if args.write_registry:
        text = registry.generate_counters_registry(root)
        path = registry.registry_path(root)
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {path}")
        return 0

    rules = tuple(engine.RULES)
    if args.rules:
        wanted = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in wanted if r not in engine.RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
        rules = wanted

    findings = engine.run_analysis(root=root, rules=rules, baseline=args.baseline)

    if args.write_baseline:
        path = args.baseline or engine.baseline_path(root)
        engine.write_baseline(path, findings)
        kept = sum(1 for f in findings if not f.suppressed)
        print(f"wrote {path} ({kept} grandfathered finding(s))")
        return 0

    fresh = engine.new_findings(findings)
    n_sup = sum(1 for f in findings if f.suppressed)
    n_base = sum(1 for f in findings if f.baselined)
    shown = findings if args.all else fresh
    for f in shown:
        print(f.render())
    print(
        f"analysis: {len(findings)} finding(s) over {len(rules)} rule(s) — "
        f"{n_sup} suppressed, {n_base} baselined, {len(fresh)} NEW"
    )
    if fresh:
        print(
            "fix the new findings, tag them with `# analysis-ok: <rule>: "
            "<reason>`, or (last resort) --write-baseline.",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
