"""CLI: ``python -m pilosa_tpu.analysis`` — the CI gate.

Exit 0 when every finding is suppressed or baselined; exit 1 on NEW
findings (and print them).  ``--write-baseline`` grandfathers the
current findings; ``--write-registry`` regenerates the counters
registry (COUNTERS.md); ``--all`` lists every finding including the
grandfathered ones.
"""

from __future__ import annotations

import argparse
import sys

from pilosa_tpu.analysis import engine, registry


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m pilosa_tpu.analysis",
        description="Project invariant linter (see DEVELOPMENT.md).",
    )
    p.add_argument("--root", default=None, help="package dir to scan (default: installed pilosa_tpu)")
    p.add_argument("--rules", default=None, help="comma-separated subset of: " + ",".join(engine.RULES))
    p.add_argument("--baseline", default=None, help="baseline file (default: <root>/analysis/baseline.json)")
    p.add_argument("--write-baseline", action="store_true", help="grandfather the current findings and exit")
    p.add_argument("--write-registry", action="store_true", help="regenerate analysis/COUNTERS.md and exit")
    p.add_argument("--all", action="store_true", help="also list suppressed/baselined findings")
    args = p.parse_args(argv)

    root = args.root or engine.package_root()

    if args.write_registry:
        text = registry.generate_counters_registry(root)
        path = registry.registry_path(root)
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {path}")
        return 0

    rules = tuple(engine.RULES)
    if args.rules:
        wanted = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in wanted if r not in engine.RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
        rules = wanted

    findings = engine.run_analysis(root=root, rules=rules, baseline=args.baseline)

    if args.write_baseline:
        path = args.baseline or engine.baseline_path(root)
        engine.write_baseline(path, findings)
        kept = sum(1 for f in findings if not f.suppressed)
        print(f"wrote {path} ({kept} grandfathered finding(s))")
        return 0

    fresh = engine.new_findings(findings)
    n_sup = sum(1 for f in findings if f.suppressed)
    n_base = sum(1 for f in findings if f.baselined)
    shown = findings if args.all else fresh
    for f in shown:
        print(f.render())
    print(
        f"analysis: {len(findings)} finding(s) over {len(rules)} rule(s) — "
        f"{n_sup} suppressed, {n_base} baselined, {len(fresh)} NEW"
    )
    if fresh:
        print(
            "fix the new findings, tag them with `# analysis-ok: <rule>: "
            "<reason>`, or (last resort) --write-baseline.",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
