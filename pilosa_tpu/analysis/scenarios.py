"""Explorer scenario registry: the concurrency-dense code under
systematic schedule control.

Each scenario builds REAL project objects (WAL, router + catch-up,
qcache, ingest stager, fragment) inside an exploration run — so every
named lock, condition, guarded-field write, and patched blocking call
they touch is a controlled yield point — runs a small fixed set of
threads, and checks invariants at the end.  Scenarios flagged
``trace_check`` additionally validate the protocol events the replica
tier emitted (analysis/spec.py) against the executable model.

The ``bug_*`` entries are SEEDED KNOWN-BUG FIXTURES (``known_bug=True``):
deliberately broken twins of real protocol code — an applied-sequence
lost-update (the unlocked read-modify-write PR 11's lockset detector
flagged in the live tree, reintroduced here), and a compaction that
ignores a lagging group's backlog (dropping records catch-up still
needs).  The live-tree gate skips them; tests/test_sched.py asserts the
explorer FINDS each one and that the printed schedule string replays
the failure deterministically.  Everything else must explore clean —
any real interleaving bug a new scenario surfaces gets fixed, keeping
the analysis baseline empty (the wal_append_vs_close scenario found
exactly one: a file-backed WAL silently buffering post-close appends to
memory, fixed in replica/wal.py).

Scenario sizing: threads and per-thread work are deliberately tiny
(2-3 threads, 1-3 protocol operations each) — the schedule space grows
exponentially and the point is the INTERLEAVINGS, not the payload.
Bounds are tuned per scenario so the tier-1 suite explores every
scenario exhaustively in seconds.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import zlib

from pilosa_tpu.analysis import lockcheck, spec
from pilosa_tpu.analysis.sched import Scenario

# -- fake serving-group transport for router scenarios -----------------------


class _FakeGroups:
    """In-process stand-in for the HTTP groups behind a router: applies
    whatever write sequence rides the forward, tracks per-group applied
    marks, and reports the usual identity/applied headers.  State
    mutation is append/dict-store only (atomic under the explorer's
    one-thread-at-a-time execution)."""

    def __init__(self, names):
        self.store = {n: [] for n in names}
        self.applied = {n: 0 for n in names}
        self.epoch = {n: f"{n}@1" for n in names}

    def forward(self, router):
        from pilosa_tpu.replica import (
            APPLIED_SEQ_HEADER,
            GROUP_HEADER,
            WRITE_SEQ_HEADER,
        )

        def _forward(g, method, path_qs, body, headers, deadline=None,
                     trace_id="", extra_headers=None, timeout_s=None):
            raw = (extra_headers or {}).get(WRITE_SEQ_HEADER) \
                or headers.get(WRITE_SEQ_HEADER)
            if raw:
                seq = int(raw)
                self.store[g.name].append(seq)
                self.applied[g.name] = max(self.applied[g.name], seq)
            rheaders = {
                GROUP_HEADER: self.epoch[g.name],
                APPLIED_SEQ_HEADER: str(self.applied[g.name]),
            }
            router._note_epoch(g, rheaders[GROUP_HEADER])
            router._note_applied(g, rheaders[APPLIED_SEQ_HEADER])
            return 200, "application/json", b"{}", rheaders

        return _forward


def _mini_router(groups=("g0", "g1", "g2"), wal=None):
    """A router over fake in-process groups: no HTTP server, no probe
    thread — scenario threads drive the protocol methods directly."""
    from pilosa_tpu.replica.router import ReplicaRouter
    from pilosa_tpu.replica.wal import WriteAheadLog

    wal = wal if wal is not None else WriteAheadLog(None, fsync=False)
    r = ReplicaRouter([f"{n}=127.0.0.1:1" for n in groups], wal=wal)
    fakes = _FakeGroups(list(groups))
    r._forward = fakes.forward(r)
    return r, fakes


# -- WAL scenarios -----------------------------------------------------------


class _WalAppendCompactCtx:
    """Two appenders race a compactor over one file-backed log: the
    compaction's three-phase copy/delta/swap (and its _sync_cv
    generation dance) under schedule control.  Recovery must see every
    appended record not legitimately compacted.  fsync is off here to
    keep the schedule space tight; the group-commit leader election has
    its own scenario below."""

    def __init__(self):
        from pilosa_tpu.replica.wal import WriteAheadLog

        self.dir = tempfile.mkdtemp(prefix="sched-wal-")
        self.path = os.path.join(self.dir, "router.wal")
        self.wal = WriteAheadLog(self.path, fsync=False)
        self.threads = [
            lambda: self.wal.append("POST", "/w1", b"a"),
            lambda: self.wal.append("POST", "/w2", b"b"),
            lambda: self.wal.compact(1),
        ]

    def check(self):
        from pilosa_tpu.replica.wal import WriteAheadLog

        self.wal.close()
        back = WriteAheadLog(self.path, fsync=False)
        try:
            live = {r.seq for r in back.records(0)}
            assert back.last_seq == 2, f"lost sequence space: {back.last_seq}"
            assert 2 in live, f"seq 2 missing after recovery: {sorted(live)}"
            assert live <= {1, 2}, f"phantom records: {sorted(live)}"
        finally:
            back.close()

    def close(self):
        self.wal.close()
        shutil.rmtree(self.dir, ignore_errors=True)


class _WalGroupCommitCtx:
    """Two appenders share one fsync'ing log: the group-commit leader
    election (one leader syscall covers both appends) explored across
    every handoff ordering.  Both records must be recoverable and the
    sequence space dense."""

    def __init__(self):
        from pilosa_tpu.replica.wal import WriteAheadLog

        self.dir = tempfile.mkdtemp(prefix="sched-walgc-")
        self.path = os.path.join(self.dir, "router.wal")
        self.wal = WriteAheadLog(self.path, fsync=True)
        self.threads = [
            lambda: self.wal.append("POST", "/w1", b"a"),
            lambda: self.wal.append("POST", "/w2", b"b"),
        ]

    def check(self):
        from pilosa_tpu.replica.wal import WriteAheadLog

        self.wal.close()
        back = WriteAheadLog(self.path, fsync=False)
        try:
            live = {r.seq for r in back.records(0)}
            assert live == {1, 2}, f"group commit lost a record: {sorted(live)}"
        finally:
            back.close()

    def close(self):
        self.wal.close()
        shutil.rmtree(self.dir, ignore_errors=True)


class _WalAppendCloseCtx:
    """An appender races close(): the append must either refuse with
    OSError or yield a durably recoverable record — never a sequence
    number whose record evaporates.  (This scenario found the real
    silent-buffer-after-close bug fixed in replica/wal.py.)"""

    def __init__(self):
        from pilosa_tpu.replica.wal import WriteAheadLog

        self.dir = tempfile.mkdtemp(prefix="sched-walclose-")
        self.path = os.path.join(self.dir, "router.wal")
        self.wal = WriteAheadLog(self.path, fsync=False)
        self.appended = []
        self.refused = []

        def appender():
            try:
                self.appended.append(self.wal.append("POST", "/w", b"x"))
            except OSError as e:
                self.refused.append(str(e))

        self.threads = [appender, self.wal.close]

    def check(self):
        from pilosa_tpu.replica.wal import WriteAheadLog

        self.wal.close()
        back = WriteAheadLog(self.path, fsync=False)
        try:
            live = {r.seq for r in back.records(0)}
            for seq in self.appended:
                assert seq in live, (
                    f"append returned seq {seq} but the record is not "
                    f"recoverable (live: {sorted(live)}) — a write was ACKed "
                    "into nothing"
                )
        finally:
            back.close()

    def close(self):
        self.wal.close()
        shutil.rmtree(self.dir, ignore_errors=True)


# -- router / catch-up scenarios --------------------------------------------


class _WriteVsCatchupCtx:
    """A writer commits sequence 3 through the sequencer while catch-up
    replays a lagging group's missed suffix (1, 2): the locked drain,
    the monotonic-max mark updates, and the rejoin flip race the
    fan-out.  Afterwards the laggard must be fully converged and every
    group must hold every live record."""

    def __init__(self):
        self.router, self.fakes = _mini_router()
        r = self.router
        # Pre-populated backlog: seqs 1..2 applied by g0/g2, missed by
        # g1 (down at the time) — the probe would have demoted it.
        for i in (1, 2):
            r.wal.append("POST", "/index/i/query", b"w%d" % i)
            spec.emit("ack", src=id(r.wal), seq=i, status=200, applied=2)
        r.shards[0].write_seq = 2
        g0, g1, g2 = r.groups
        for g in (g0, g2):
            g.applied_seq = 2
            self.fakes.applied[g.name] = 2
            self.fakes.store[g.name] = [1, 2]
        g1.caught_up = False

        def writer():
            status, _c, _p, _h = r._route_write(
                "POST", "/index/i/query", b"w3",
                {"content-type": "application/json"},
            )
            assert status == 200, f"write refused mid-scenario: {status}"

        self.threads = [writer, lambda: r.catchup.catch_up(r.groups[1])]

    def check(self):
        r = self.router
        g1 = r.groups[1]
        assert r.wal.last_seq == 3
        assert g1.caught_up, "catch-up round failed"
        assert g1.applied_seq == 3, (
            f"laggard rejoined at applied {g1.applied_seq} < head 3"
        )
        assert self.fakes.applied["g1"] == 3
        for n in ("g0", "g1", "g2"):
            assert set(self.fakes.store[n]) >= {1, 2, 3}, (
                f"{n} missing writes: {sorted(self.fakes.store[n])}"
            )

    def close(self):
        self.router.wal.close()


class _AppliedSeqNotesCtx:
    """Three handler threads note applied-sequence headers for one
    group concurrently: the locked monotonic-max must keep the highest
    mark under every interleaving (the live-tree twin of the
    bug_applied_seq_lost_update fixture)."""

    def __init__(self):
        self.router, _ = _mini_router(("g0", "g1"))
        g0 = self.router.groups[0]
        self.threads = [
            lambda: self.router._note_applied(g0, "5"),
            lambda: self.router._note_applied(g0, "9"),
            lambda: self.router._note_applied(g0, "7"),
        ]

    def check(self):
        got = self.router.groups[0].applied_seq
        assert got == 9, f"lost applied-seq update: {got} != 9"

    def close(self):
        self.router.wal.close()


# -- qcache scenario ---------------------------------------------------------


@lockcheck.guarded_class
class _FakeFragment:
    """Minimal fragment for generation_vector: the generation rebind is
    declared guarded so the writer thread's bump is a yield point."""

    _guarded_by_ = {"generation": "scenario.fakefrag._mu"}

    def __init__(self):
        self.generation = 0


class _FakeView:
    def __init__(self, frag):
        self.fragments = {0: frag}


class _FakeFrame:
    def __init__(self, frag):
        self.row_label = "rowID"
        self.inverse_enabled = False
        self.time_quantum = ""
        self.views = {"standard": _FakeView(frag)}


class _FakeIndex:
    column_label = "columnID"
    time_quantum = ""

    def max_slice(self):
        return 0

    def max_inverse_slice(self):
        return 0


class _FakeHolder:
    def __init__(self, frag):
        self._idx = _FakeIndex()
        self._frame = _FakeFrame(frag)

    def index(self, name):
        return self._idx

    def frame(self, index, name):
        return self._frame


_QUERY = 'Count(Bitmap(id=1, frame="f"))'


class _QcacheStoreVsWriteCtx:
    """A cacheable miss executes and commits while a writer bumps the
    referenced fragment's generation: commit must decline whenever the
    write landed mid-execution, and the explored history must
    linearize against the sequential store/bump/get spec — a stale
    stored result under ANY interleaving is a read-your-writes break."""

    def __init__(self):
        from pilosa_tpu import qcache
        # Warm the executor import on the driver thread: a first-thread
        # import inside the reader would give execution #1 a different
        # yield structure than #2..N.  The parse memo needs no warm-up
        # anymore — a NamedGlobal bypasses itself under an active
        # exploration run, so every execution takes the identical
        # miss-parse path by construction.
        from pilosa_tpu.executor import DEFAULT_FRAME  # noqa: F401

        self.frag = _FakeFragment()
        self.holder = _FakeHolder(self.frag)
        self.cache = qcache.QueryCache(min_cost_ms=0)
        self.history = spec.LinHistory()

        def reader():
            results, pending = self.cache.lookup(
                self.holder, "i", _QUERY, None
            )
            assert results is None  # cold cache: always a miss
            gen = self.frag.generation  # the "execution" reads state here
            value = f"v{gen}"
            opid = self.history.invoke(0, "store", (value, gen))
            stored = pending is not None and self.cache.commit(
                self.holder, pending, [value]
            )
            self.history.respond(opid, bool(stored))

        def writer():
            opid = self.history.invoke(1, "bump")
            self.frag.generation += 1
            self.history.respond(opid, None)

        self.threads = [reader, writer]

    def check(self):
        results, _pending = self.cache.lookup(self.holder, "i", _QUERY, None)
        opid = self.history.invoke(2, "get")
        self.history.respond(opid, results[0] if results else None)
        if results:
            want = f"v{self.frag.generation}"
            assert results[0] == want, (
                f"stale cache hit: {results[0]} with generation "
                f"{self.frag.generation} current — a write was lost"
            )
        ok, detail = spec.check_linearizable(
            self.history, (None, 0), spec.qcache_apply
        )
        assert ok, f"qcache history not linearizable: {detail}"

    def close(self):
        pass


# -- ingest stager scenario --------------------------------------------------


class _IngestResumeVsApplyCtx:
    """Two senders race the same two-chunk transfer (a retrying client
    re-sends chunk 0 while the original is mid-flight or already
    applied): the busy flag must never leak, offsets must only advance
    chunk-by-chunk, and chunk 1 must apply exactly once."""

    def __init__(self):
        from pilosa_tpu.ingest import StreamIngestor, encode_packed

        self.applies = []
        self.errors = []
        self.ing = StreamIngestor(
            apply=lambda key, rows, cols, deadline: self.applies.append(
                (key, int(rows[0]))
            )
        )
        c0 = encode_packed([0], [5])
        c1 = encode_packed([1], [6])
        self.c0, self.c1 = c0, c1
        total = len(c0) + len(c1)
        crc = zlib.crc32(c1, zlib.crc32(c0))

        def send(chunks):
            def fn():
                from pilosa_tpu.ingest import IngestError

                for off, body in chunks:
                    try:
                        self.ing.chunk(("i", "f"), off, total, crc, body,
                                       chunk_crc=zlib.crc32(body))
                    except IngestError as e:
                        self.errors.append(e.status)
            return fn

        self.threads = [
            send([(0, c0), (len(c0), c1)]),  # the real sender
            send([(0, c0)]),  # a retry racing it
        ]

    def check(self):
        later = [n for _k, n in self.applies if n == 1]
        assert len(later) <= 1, (
            f"chunk 1 applied {len(later)} times: {self.applies}"
        )
        if not self.errors:
            # No sender was turned away: the transfer must have
            # completed exactly once.
            assert len(later) == 1, (
                f"error-free run never applied chunk 1: {self.applies}"
            )
        assert all(s == 409 for s in self.errors), (
            f"unexpected ingest error statuses: {self.errors}"
        )
        # A sender bounced by the busy gate (or an offset gap) resumes
        # in real life; here the transfer may legitimately end parked —
        # but NEVER with the busy flag leaked or at an offset that is
        # not a chunk boundary.
        for st in self.ing._transfers.values():
            assert not st["busy"], "busy flag leaked on a settled transfer"
            assert st["off"] in (0, len(self.c0)), (
                f"residual transfer at non-boundary offset {st['off']}"
            )

    def close(self):
        pass


# -- fragment linearizability scenario ---------------------------------------


class _FragmentLinCtx:
    """Concurrent set/clear/count on one fragment, checked linearizable
    against the sequential bitmap spec (the fragment's RLock makes each
    op atomic; the checker proves the HISTORY is, under every explored
    schedule)."""

    def __init__(self):
        from pilosa_tpu.core.fragment import Fragment

        self.dir = tempfile.mkdtemp(prefix="sched-frag-")
        self.frag = Fragment(
            os.path.join(self.dir, "0"), "i", "f", "standard", 0
        )
        self.frag.open()
        self.history = spec.LinHistory()

        def op(tid, name, *args):
            def fn():
                opid = self.history.invoke(tid, name, args)
                if name == "set":
                    r = self.frag.set_bit(*args)
                elif name == "clear":
                    r = self.frag.clear_bit(*args)
                else:
                    r = self.frag.count()
                self.history.respond(opid, r)
            return fn

        self.threads = [op(0, "set", 0, 1), op(1, "clear", 0, 1),
                        op(2, "count")]

    def check(self):
        ok, detail = spec.check_linearizable(
            self.history, frozenset(), spec.bitmap_apply
        )
        assert ok, f"fragment history not linearizable: {detail}"

    def close(self):
        self.frag.close()
        shutil.rmtree(self.dir, ignore_errors=True)


# -- seeded known-bug fixtures ----------------------------------------------


class _BugAppliedSeqLostUpdateCtx:
    """KNOWN BUG twin of _AppliedSeqNotesCtx: the applied-sequence
    read-modify-write WITHOUT the router table lock — exactly the
    unlocked monotonic-max PR 11's lockset detector caught in the live
    router.  The explorer must find the interleaving that loses the
    higher mark and print a schedule that replays it."""

    def __init__(self):
        from pilosa_tpu.replica.router import GroupState

        self.g = GroupState("g0", "127.0.0.1:1")

        def note(n):
            def fn():
                cur = self.g.applied_seq  # read ...
                self.g.applied_seq = max(cur, n)  # ... racy write
            return fn

        self.threads = [note(5), note(9)]

    def check(self):
        got = self.g.applied_seq
        assert got == 9, (
            f"applied-seq lost update: mark regressed to {got} (wanted 9) — "
            "the read-modify-write ran without replica.router._mu"
        )

    def close(self):
        pass


class _BugCompactDropsUnreplayedCtx:
    """KNOWN BUG: a compaction that floors at the WAL head, ignoring a
    demoted laggard's backlog (and any resync floors).  In schedules
    where it beats the catch-up round, the laggard 'rejoins' while
    missing acked writes — caught three ways: the end-state invariant,
    the trace checker's compact_plan floor rule, and the read events
    that follow."""

    def __init__(self):
        self.router, self.fakes = _mini_router()
        r = self.router
        for i in (1, 2, 3):
            r.wal.append("POST", "/index/i/query", b"w%d" % i)
            spec.emit("ack", src=id(r.wal), seq=i, status=200, applied=2)
        r.shards[0].write_seq = 3
        g0, g1, g2 = r.groups
        for g in (g0, g2):
            g.applied_seq = 3
            self.fakes.applied[g.name] = 3
            self.fakes.store[g.name] = [1, 2, 3]
        g1.applied_seq = 1
        g1.caught_up = False
        self.fakes.applied["g1"] = 1
        self.fakes.store["g1"] = [1]

        def buggy_compactor():
            with r._mu:
                tracked = {g.name: g.applied_seq for g in r.groups}
            floor = r.wal.last_seq  # BUG: ignores g1's lag + resync floors
            spec.emit("compact_plan", src=id(r.wal), floor=floor,
                      tracked=tracked, floors=[])
            r.wal.compact(floor)

        self.threads = [buggy_compactor,
                        lambda: r.catchup.catch_up(r.groups[1])]

    def check(self):
        g1 = self.router.groups[1]
        assert not (g1.caught_up and g1.applied_seq < 3), (
            f"compaction dropped records g1 still needed: rejoined at "
            f"applied {g1.applied_seq} with head 3 — acked writes lost"
        )

    def close(self):
        self.router.wal.close()


# -- registry ----------------------------------------------------------------

SCENARIOS: dict[str, Scenario] = {
    s.name: s
    for s in (
        Scenario("wal_append_vs_compact", _WalAppendCompactCtx,
                 trace_check=True, bound=1, max_schedules=600),
        Scenario("wal_group_commit", _WalGroupCommitCtx,
                 bound=1, max_schedules=200),
        Scenario("wal_append_vs_close", _WalAppendCloseCtx,
                 bound=2, max_schedules=600),
        Scenario("router_write_vs_catchup", _WriteVsCatchupCtx,
                 trace_check=True, bound=1, max_schedules=800),
        Scenario("applied_seq_notes", _AppliedSeqNotesCtx,
                 trace_check=True, bound=2, max_schedules=800),
        Scenario("qcache_store_vs_write", _QcacheStoreVsWriteCtx,
                 bound=2, max_schedules=800),
        Scenario("ingest_resume_vs_apply", _IngestResumeVsApplyCtx,
                 bound=2, max_schedules=800),
        Scenario("fragment_set_clear_count", _FragmentLinCtx,
                 bound=1, max_schedules=600),
        Scenario("bug_applied_seq_lost_update", _BugAppliedSeqLostUpdateCtx,
                 known_bug=True, bound=2, max_schedules=400),
        Scenario("bug_compact_drops_unreplayed", _BugCompactDropsUnreplayedCtx,
                 known_bug=True, trace_check=True, bound=1,
                 max_schedules=600),
    )
}


def get(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {', '.join(sorted(SCENARIOS))}"
        )


def live_scenarios() -> list[Scenario]:
    """The tier-1 gate set: every scenario that must explore clean."""
    return [s for n, s in sorted(SCENARIOS.items()) if not s.known_bug]


def known_bug_scenarios() -> list[Scenario]:
    return [s for n, s in sorted(SCENARIOS.items()) if s.known_bug]
