"""The five project-invariant rules.

Each rule returns Finding objects; the engine applies suppressions,
fingerprints, and the baseline.  See DEVELOPMENT.md ("Static analysis &
concurrency checking") for the catalog and the rationale per rule.
"""

from __future__ import annotations

import ast
import os

from pilosa_tpu.analysis.callgraph import CallGraph
from pilosa_tpu.analysis.engine import Finding
from pilosa_tpu.analysis import registry as regmod

LOCKSTEP_ENTRY_FILE = "parallel/service.py"
LOCKSTEP_ENTRY_PREFIX = "_exec_batch"

HOP_METHODS = ("execute_query", "execute_remote", "execute_remote_call")
DEADLINE_PARAMS = ("deadline", "opt", "opts", "options")

_LOG_METHODS = ("warning", "error", "exception", "critical", "info", "debug")


def run_rule(rule: str, files, root: str) -> list[Finding]:
    fn = {
        "lockstep-determinism": rule_lockstep_determinism,
        "lock-discipline": rule_lock_discipline,
        "stats-registry": rule_stats_registry,
        "exception-hygiene": rule_exception_hygiene,
        "deadline-propagation": rule_deadline_propagation,
    }[rule]
    return fn(files, root)


# -- 1. lockstep-determinism ------------------------------------------------
#
# Every rank must resolve every decision identically: rank 0 decides,
# flags ride the wire (coalescing PR 2, expiry PR 3, sampling PR 5,
# epochs PR 6).  Rank-local nondeterminism in code reachable from the
# batch execution entry points is how that invariant silently breaks.


def _unparse(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _is_set_expr(node: ast.expr) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("set", "frozenset")
    return False


class _DeterminismVisitor(ast.NodeVisitor):
    """Scans ONE function body (nested defs are their own call-graph
    nodes and scanned separately; lambdas are inlined here)."""

    def __init__(self, rel: str, scope: str, out: list):
        self.rel = rel
        self.scope = scope
        self.out = out
        self._top = True

    def _flag(self, node, msg: str) -> None:
        self.out.append(
            Finding("lockstep-determinism", self.rel, node.lineno, self.scope, msg)
        )

    def visit_FunctionDef(self, node):
        if self._top:
            self._top = False
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        pass

    def visit_Call(self, node: ast.Call) -> None:
        text = _unparse(node.func)
        if text in ("time.time", "time.time_ns"):
            self._flag(node, "rank-local wall clock (decide on rank 0, ship the flag)")
        elif text.startswith("random.") or text.startswith(("np.random.", "numpy.random.")):
            self._flag(node, f"unseeded module-level randomness ({text}) diverges across ranks")
        elif text.startswith(("uuid.", "secrets.")) or text == "os.urandom":
            self._flag(node, f"{text}() is rank-local entropy")
        elif text in ("os.getenv", "os.environ.get"):
            self._flag(node, "environment read: ranks may be launched with differing env")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if _unparse(node.value) == "os.environ":
            self._flag(node, "environment read: ranks may be launched with differing env")
        self.generic_visit(node)

    def _check_iter(self, it: ast.expr) -> None:
        if _is_set_expr(it):
            self._flag(
                it,
                "iteration over a set: order depends on PYTHONHASHSEED and "
                "diverges across rank processes (sort it first)",
            )
        elif isinstance(it, ast.Call) and isinstance(it.func, ast.Name):
            if it.func.id in ("list", "tuple", "enumerate", "iter") and it.args \
                    and _is_set_expr(it.args[0]):
                self._flag(
                    it,
                    "set materialized in iteration order: order depends on "
                    "PYTHONHASHSEED across rank processes (sort it first)",
                )

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)

    def visit_comprehension(self, node) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)


def rule_lockstep_determinism(files, root: str) -> list[Finding]:
    graph = CallGraph(files)
    seeds = graph.seeds_matching(LOCKSTEP_ENTRY_FILE, LOCKSTEP_ENTRY_PREFIX)
    if not seeds:
        return []
    reachable = graph.reachable_from(seeds)
    out: list[Finding] = []
    for key in sorted(reachable):
        info = graph.funcs[key]
        _DeterminismVisitor(info.rel, info.scope, out).visit(info.node)
    return out


# -- 2. lock-discipline (static half) --------------------------------------
#
# The runtime half is lockcheck.py (PILOSA_TPU_LOCK_CHECK=1).  This
# half keeps its coverage honest: a raw threading primitive is a lock
# the checker cannot see.

_RAW_PRIMS = ("threading.Lock", "threading.RLock", "threading.Condition")
_EXEMPT_FILES = ("analysis/lockcheck.py",)


def rule_lock_discipline(files, root: str) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        if sf.rel in _EXEMPT_FILES:
            continue

        from pilosa_tpu.analysis.engine import ScopedVisitor

        class V(ScopedVisitor):
            def visit_Call(inner, node):
                text = _unparse(node.func)
                if text in _RAW_PRIMS:
                    kind = text.rsplit(".", 1)[-1]
                    factory = {
                        "Lock": "named_lock",
                        "RLock": "named_rlock",
                        "Condition": "named_condition",
                    }[kind]
                    out.append(
                        Finding(
                            "lock-discipline", sf.rel, node.lineno,
                            inner.scope_name(),
                            f"raw threading.{kind}() invisible to the lock "
                            f"checker; use lockcheck.{factory}(\"<name>\")",
                        )
                    )
                inner.generic_visit(node)

        V().visit(sf.tree)
    return out


# -- 3. stats-registry ------------------------------------------------------


def rule_stats_registry(files, root: str) -> list[Finding]:
    out: list[Finding] = []
    sites, unresolved = regmod.collect_stat_sites(files)
    rpath = regmod.registry_path(root)
    rel_reg = "analysis/" + regmod.REGISTRY_NAME
    if not os.path.exists(rpath):
        out.append(
            Finding(
                "stats-registry", rel_reg, 1, "<registry>",
                "counters registry missing; generate it with "
                "`python -m pilosa_tpu.analysis --write-registry`",
            )
        )
        return out
    with open(rpath, encoding="utf-8") as f:
        committed = f.read()
    names = regmod.registered_names(committed)
    for s in sites:
        if s.name not in names:
            out.append(
                Finding(
                    "stats-registry", s.rel, s.line, s.scope,
                    f"stats name `{s.name}` not in the counters registry — "
                    "typo, or regenerate with `python -m pilosa_tpu.analysis "
                    "--write-registry`",
                )
            )
    for rel, line, scope, kind in unresolved:
        out.append(
            Finding(
                "stats-registry", rel, line, scope,
                f"stats .{kind}() name is not statically recoverable; use a "
                "literal or f-string so the registry can document it",
            )
        )
    regenerated = regmod.render_registry(sites)
    if regenerated != committed:
        added = sorted(regmod.registered_names(regenerated) - names)
        removed = sorted(names - regmod.registered_names(regenerated))
        detail = []
        if added:
            detail.append(f"missing from registry: {', '.join(added[:6])}")
        if removed:
            detail.append(f"stale in registry: {', '.join(removed[:6])}")
        out.append(
            Finding(
                "stats-registry", rel_reg, 1, "<registry>",
                "counters registry is stale ("
                + ("; ".join(detail) or "formatting drift")
                + ") — regenerate with `python -m pilosa_tpu.analysis "
                "--write-registry` and commit the diff",
            )
        )
    return out


# -- 4. exception-hygiene ---------------------------------------------------
#
# The syncer's five silent peer-skips were a PR 5 satellite; this rule
# stops the pattern recurring: a broad handler must leave a trace — a
# stat, a log line, a re-raise, USE of the caught exception (collected,
# returned to the caller, ...), or an explicit analysis-ok tag.


def _body_has_raise(body) -> bool:
    for node in body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return True
    return False


def _body_uses_name(body, name: str) -> bool:
    if not name:
        return False
    for node in body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
    return False


def _body_records(body) -> bool:
    """A stats emission, a logging-ish call, or a recording helper
    (``self._note_peer_error(...)``-style ``_note_*`` methods, the
    project idiom for counted skips) anywhere in the handler."""
    for node in body:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Attribute):
                if fn.attr in regmod.STAT_METHODS and regmod._receiver_is_stats(fn.value):
                    return True
                if fn.attr in _LOG_METHODS or fn.attr == "print_exc":
                    return True
                if fn.attr.startswith("_note"):
                    return True
            elif isinstance(fn, ast.Name) and fn.id == "print":
                return True
    return False


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in ("Exception", "BaseException")
            for e in t.elts
        )
    return False


def rule_exception_hygiene(files, root: str) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        if sf.rel.startswith("analysis/"):
            continue

        from pilosa_tpu.analysis.engine import ScopedVisitor

        class V(ScopedVisitor):
            def visit_ExceptHandler(inner, node):
                if _is_broad_handler(node) and not (
                    _body_has_raise(node.body)
                    or _body_uses_name(node.body, node.name)
                    or _body_records(node.body)
                ):
                    out.append(
                        Finding(
                            "exception-hygiene", sf.rel, node.lineno,
                            inner.scope_name(),
                            "broad except swallows the error with no stat, "
                            "log, re-raise, or use of the exception — count "
                            "it or tag the site",
                        )
                    )
                inner.generic_visit(node)

        V().visit(sf.tree)
    return out


# -- 5. deadline-propagation ------------------------------------------------
#
# PR 3's contract: every hop forwards the REMAINING budget.  A function
# that holds a deadline (parameter or ExecOptions) and performs an HTTP
# hop without `deadline=` silently resets the budget on the peer.


class _DeadlineVisitor(ast.NodeVisitor):
    def __init__(self, rel: str, out: list):
        self.rel = rel
        self.out = out
        self.scope: list[str] = []

    def visit_ClassDef(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node):
        self.scope.append(node.name)
        args = node.args
        names = [
            a.arg
            for a in (
                args.posonlyargs + args.args + args.kwonlyargs
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            )
        ]
        has_deadline = any(n in DEADLINE_PARAMS for n in names)
        if not has_deadline:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) and sub.attr == "deadline":
                    has_deadline = True
                    break
        if has_deadline:
            scope = ".".join(self.scope)
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                if not (isinstance(fn, ast.Attribute) and fn.attr in HOP_METHODS):
                    continue
                kw_names = {k.arg for k in sub.keywords}
                if "deadline" not in kw_names and None not in kw_names:
                    self.out.append(
                        Finding(
                            "deadline-propagation", self.rel, sub.lineno, scope,
                            f".{fn.attr}(...) hop without deadline= — the peer "
                            "restarts the budget instead of inheriting the "
                            "remaining one",
                        )
                    )
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def rule_deadline_propagation(files, root: str) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        if sf.rel.startswith("analysis/"):
            continue
        _DeadlineVisitor(sf.rel, out).visit(sf.tree)
    return out
