"""The project-invariant rules (generation 4: eleven of them).

Each rule returns Finding objects; the engine applies suppressions,
fingerprints, and the baseline.  See DEVELOPMENT.md ("Static analysis &
concurrency checking", "Race detection & native conformance", and
"Free-threading readiness") for the catalog and the rationale per rule.
(The twelfth check, ``stale-suppression``, lives in the engine itself:
it needs the post-suppression state of every other rule's findings.)
"""

from __future__ import annotations

import ast
import os

from pilosa_tpu.analysis.callgraph import CallGraph
from pilosa_tpu.analysis.engine import Finding
from pilosa_tpu.analysis import registry as regmod

LOCKSTEP_ENTRY_FILE = "parallel/service.py"
LOCKSTEP_ENTRY_PREFIX = "_exec_batch"

# Budget-carrying hops: the executor→client edges forward a Deadline;
# the replica tier's forward paths (router._forward, the catch-up
# replay) forward either the remaining Deadline or an explicit socket
# bound (timeout_s) — a hop with neither resets the budget on the peer
# (or holds the sequencer lock for the full 30 s default timeout).
HOP_METHODS = ("execute_query", "execute_remote", "execute_remote_call",
               "_forward", "_replay_one")
DEADLINE_PARAMS = ("deadline", "opt", "opts", "options", "timeout_s")
# Keywords that count as forwarding the budget on a hop.
_BUDGET_KWARGS = ("deadline", "timeout_s")

_LOG_METHODS = ("warning", "error", "exception", "critical", "info", "debug")


def run_rule(rule: str, files, root: str) -> list[Finding]:
    fn = {
        "lockstep-determinism": rule_lockstep_determinism,
        "lock-discipline": rule_lock_discipline,
        "stats-registry": rule_stats_registry,
        "exception-hygiene": rule_exception_hygiene,
        "deadline-propagation": rule_deadline_propagation,
        "guarded-fields": rule_guarded_fields,
        "native-abi": rule_native_abi,
        "global-mutable-state": rule_global_mutable_state,
        "check-then-act": rule_check_then_act,
        "env-knob-outside-config": rule_env_knob_outside_config,
    }[rule]
    return fn(files, root)


# -- 1. lockstep-determinism ------------------------------------------------
#
# Every rank must resolve every decision identically: rank 0 decides,
# flags ride the wire (coalescing PR 2, expiry PR 3, sampling PR 5,
# epochs PR 6).  Rank-local nondeterminism in code reachable from the
# batch execution entry points is how that invariant silently breaks.


def _unparse(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _is_set_expr(node: ast.expr) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("set", "frozenset")
    return False


class _DeterminismVisitor(ast.NodeVisitor):
    """Scans ONE function body (nested defs are their own call-graph
    nodes and scanned separately; lambdas are inlined here)."""

    def __init__(self, rel: str, scope: str, out: list):
        self.rel = rel
        self.scope = scope
        self.out = out
        self._top = True

    def _flag(self, node, msg: str) -> None:
        self.out.append(
            Finding("lockstep-determinism", self.rel, node.lineno, self.scope, msg)
        )

    def visit_FunctionDef(self, node):
        if self._top:
            self._top = False
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        pass

    def visit_Call(self, node: ast.Call) -> None:
        text = _unparse(node.func)
        if text in ("time.time", "time.time_ns"):
            self._flag(node, "rank-local wall clock (decide on rank 0, ship the flag)")
        elif text.startswith("random.") or text.startswith(("np.random.", "numpy.random.")):
            self._flag(node, f"unseeded module-level randomness ({text}) diverges across ranks")
        elif text.startswith(("uuid.", "secrets.")) or text == "os.urandom":
            self._flag(node, f"{text}() is rank-local entropy")
        elif text in ("os.getenv", "os.environ.get"):
            self._flag(node, "environment read: ranks may be launched with differing env")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if _unparse(node.value) == "os.environ":
            self._flag(node, "environment read: ranks may be launched with differing env")
        self.generic_visit(node)

    def _check_iter(self, it: ast.expr) -> None:
        if _is_set_expr(it):
            self._flag(
                it,
                "iteration over a set: order depends on PYTHONHASHSEED and "
                "diverges across rank processes (sort it first)",
            )
        elif isinstance(it, ast.Call) and isinstance(it.func, ast.Name):
            if it.func.id in ("list", "tuple", "enumerate", "iter") and it.args \
                    and _is_set_expr(it.args[0]):
                self._flag(
                    it,
                    "set materialized in iteration order: order depends on "
                    "PYTHONHASHSEED across rank processes (sort it first)",
                )

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)

    def visit_comprehension(self, node) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)


def rule_lockstep_determinism(files, root: str) -> list[Finding]:
    graph = CallGraph(files)
    seeds = graph.seeds_matching(LOCKSTEP_ENTRY_FILE, LOCKSTEP_ENTRY_PREFIX)
    if not seeds:
        return []
    reachable = graph.reachable_from(seeds)
    out: list[Finding] = []
    for key in sorted(reachable):
        info = graph.funcs[key]
        _DeterminismVisitor(info.rel, info.scope, out).visit(info.node)
    return out


# -- 2. lock-discipline (static half) --------------------------------------
#
# The runtime half is lockcheck.py (PILOSA_TPU_LOCK_CHECK=1).  This
# half keeps its coverage honest: a raw threading primitive is a lock
# the checker cannot see.

_RAW_PRIMS = ("threading.Lock", "threading.RLock", "threading.Condition")
# lockcheck IS the instrumentation; sched.py is the interleaving
# explorer whose own machinery (baton semaphores, the SchedLock
# fall-through inners) must be invisible to the checker by
# construction — instrumenting the scheduler with itself would turn
# every grant into a yield point.
_EXEMPT_FILES = ("analysis/lockcheck.py", "analysis/sched.py")


def rule_lock_discipline(files, root: str) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        if sf.rel in _EXEMPT_FILES:
            continue

        from pilosa_tpu.analysis.engine import ScopedVisitor

        class V(ScopedVisitor):
            def visit_Call(inner, node):
                text = _unparse(node.func)
                if text in _RAW_PRIMS:
                    kind = text.rsplit(".", 1)[-1]
                    factory = {
                        "Lock": "named_lock",
                        "RLock": "named_rlock",
                        "Condition": "named_condition",
                    }[kind]
                    out.append(
                        Finding(
                            "lock-discipline", sf.rel, node.lineno,
                            inner.scope_name(),
                            f"raw threading.{kind}() invisible to the lock "
                            f"checker; use lockcheck.{factory}(\"<name>\")",
                        )
                    )
                inner.generic_visit(node)

        V().visit(sf.tree)
    return out


# -- 3. stats-registry ------------------------------------------------------


def rule_stats_registry(files, root: str) -> list[Finding]:
    out: list[Finding] = []
    sites, unresolved = regmod.collect_stat_sites(files)
    rpath = regmod.registry_path(root)
    rel_reg = "analysis/" + regmod.REGISTRY_NAME
    if not os.path.exists(rpath):
        out.append(
            Finding(
                "stats-registry", rel_reg, 1, "<registry>",
                "counters registry missing; generate it with "
                "`python -m pilosa_tpu.analysis --write-registry`",
            )
        )
        return out
    with open(rpath, encoding="utf-8") as f:
        committed = f.read()
    names = regmod.registered_names(committed)
    for s in sites:
        if s.name not in names:
            out.append(
                Finding(
                    "stats-registry", s.rel, s.line, s.scope,
                    f"stats name `{s.name}` not in the counters registry — "
                    "typo, or regenerate with `python -m pilosa_tpu.analysis "
                    "--write-registry`",
                )
            )
    for rel, line, scope, kind in unresolved:
        out.append(
            Finding(
                "stats-registry", rel, line, scope,
                f"stats .{kind}() name is not statically recoverable; use a "
                "literal or f-string so the registry can document it",
            )
        )
    # Exposition drift gate: /metrics names derive MECHANICALLY from
    # these registry names (metrics.prom_name), so the only ways the
    # exposition can drift from the registry are a registered series
    # whose mangled form is not a valid Prometheus metric name, or two
    # DISTINCT registered series colliding onto one mangled name.
    from pilosa_tpu import metrics as metrics_mod

    kinds_by_name: dict[str, str] = {}
    for s in sites:
        k = "counter" if s.kind == "count" else s.kind
        # A name emitted as both a counter and something else maps with
        # its counter suffix (_total widens the namespace, so prefer it
        # for the collision check).
        if kinds_by_name.get(s.name) != "counter":
            kinds_by_name[s.name] = k
    for a, b, prom in metrics_mod.registry_collisions(kinds_by_name):
        if not b:
            out.append(
                Finding(
                    "stats-registry", rel_reg, 1, "<exposition>",
                    f"stats name `{a}` renders an invalid Prometheus "
                    f"metric name `{prom}` at /metrics — rename the series",
                )
            )
        else:
            out.append(
                Finding(
                    "stats-registry", rel_reg, 1, "<exposition>",
                    f"stats names `{a}` and `{b}` collide at /metrics as "
                    f"`{prom}` — rename one of them",
                )
            )
    regenerated = regmod.render_registry(sites)
    if regenerated != committed:
        added = sorted(regmod.registered_names(regenerated) - names)
        removed = sorted(names - regmod.registered_names(regenerated))
        detail = []
        if added:
            detail.append(f"missing from registry: {', '.join(added[:6])}")
        if removed:
            detail.append(f"stale in registry: {', '.join(removed[:6])}")
        out.append(
            Finding(
                "stats-registry", rel_reg, 1, "<registry>",
                "counters registry is stale ("
                + ("; ".join(detail) or "formatting drift")
                + ") — regenerate with `python -m pilosa_tpu.analysis "
                "--write-registry` and commit the diff",
            )
        )
    return out


# -- 4. exception-hygiene ---------------------------------------------------
#
# The syncer's five silent peer-skips were a PR 5 satellite; this rule
# stops the pattern recurring: a broad handler must leave a trace — a
# stat, a log line, a re-raise, USE of the caught exception (collected,
# returned to the caller, ...), or an explicit analysis-ok tag.


def _body_has_raise(body) -> bool:
    for node in body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return True
    return False


def _body_uses_name(body, name: str) -> bool:
    if not name:
        return False
    for node in body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
    return False


def _body_records(body) -> bool:
    """A stats emission, a logging-ish call, or a recording helper
    (``self._note_peer_error(...)``-style ``_note_*`` methods, the
    project idiom for counted skips) anywhere in the handler."""
    for node in body:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Attribute):
                if fn.attr in regmod.STAT_METHODS and regmod._receiver_is_stats(fn.value):
                    return True
                if fn.attr in _LOG_METHODS or fn.attr == "print_exc":
                    return True
                if fn.attr.startswith("_note"):
                    return True
            elif isinstance(fn, ast.Name) and fn.id == "print":
                return True
    return False


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in ("Exception", "BaseException")
            for e in t.elts
        )
    return False


def rule_exception_hygiene(files, root: str) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        if sf.rel.startswith("analysis/"):
            continue

        from pilosa_tpu.analysis.engine import ScopedVisitor

        class V(ScopedVisitor):
            def visit_ExceptHandler(inner, node):
                if _is_broad_handler(node) and not (
                    _body_has_raise(node.body)
                    or _body_uses_name(node.body, node.name)
                    or _body_records(node.body)
                ):
                    out.append(
                        Finding(
                            "exception-hygiene", sf.rel, node.lineno,
                            inner.scope_name(),
                            "broad except swallows the error with no stat, "
                            "log, re-raise, or use of the exception — count "
                            "it or tag the site",
                        )
                    )
                inner.generic_visit(node)

        V().visit(sf.tree)
    return out


# -- 5. deadline-propagation ------------------------------------------------
#
# PR 3's contract: every hop forwards the REMAINING budget.  A function
# that holds a deadline (parameter or ExecOptions) and performs an HTTP
# hop without `deadline=` silently resets the budget on the peer.


class _DeadlineVisitor(ast.NodeVisitor):
    def __init__(self, rel: str, out: list):
        self.rel = rel
        self.out = out
        self.scope: list[str] = []

    def visit_ClassDef(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node):
        self.scope.append(node.name)
        args = node.args
        names = [
            a.arg
            for a in (
                args.posonlyargs + args.args + args.kwonlyargs
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            )
        ]
        has_deadline = any(n in DEADLINE_PARAMS for n in names)
        if not has_deadline:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) and sub.attr == "deadline":
                    has_deadline = True
                    break
        if has_deadline:
            scope = ".".join(self.scope)
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                if not (isinstance(fn, ast.Attribute) and fn.attr in HOP_METHODS):
                    continue
                kw_names = {k.arg for k in sub.keywords}
                if not kw_names.intersection(_BUDGET_KWARGS) and None not in kw_names:
                    self.out.append(
                        Finding(
                            "deadline-propagation", self.rel, sub.lineno, scope,
                            f".{fn.attr}(...) hop without deadline= (or "
                            "timeout_s= on the replica forward paths) — the "
                            "peer restarts the budget instead of inheriting "
                            "the remaining one",
                        )
                    )
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def rule_deadline_propagation(files, root: str) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        if sf.rel.startswith("analysis/"):
            continue
        _DeadlineVisitor(sf.rel, out).visit(sf.tree)
    return out


# -- 6. guarded-fields (static half of the lockset race detector) ------------
#
# lockcheck's runtime half sees attribute REBINDS under the enabled
# checker; this half covers what setattr interception cannot — in-place
# container mutation (`self._store.pop(...)`, `self._transfers[k] = v`)
# — and what a test run may never execute.  A field declared in
# ``_guarded_by_`` that is mutated in a method with NO named-lock
# acquisition anywhere on its intra-package call paths is a finding.
#
# Over-approximation notes (both directions documented): lock
# acquisition is matched by NAME SHAPE (`with self.<lock-ish attr>` /
# `.acquire()` where the attribute looks like a lock: contains "mu",
# "lock", "cv", or "cond"), not by lock identity — a caller holding a
# DIFFERENT `_mu` shadows a real miss (fewer findings, same honesty
# trade as the callgraph stoplist); reachability is the same name-based
# call graph, so an unreachable-looking mutator errs toward MORE
# findings, absorbed by suppressions.  Lifecycle methods (`__init__`,
# `open`, `close`, context-manager plumbing) are exempt — the static
# analog of the runtime init-phase single-thread exemption.

_LIFECYCLE_EXEMPT = ("__init__", "__new__", "__enter__", "__exit__",
                     "open", "close")

# Method names that mutate their receiver in place.
_MUTATOR_METHODS = frozenset({
    "pop", "popitem", "clear", "update", "setdefault", "append", "extend",
    "insert", "remove", "discard", "add", "move_to_end", "sort", "reverse",
})

_LOCKISH_RE = None  # compiled lazily (module import cost)


def _is_lockish_name(name: str) -> bool:
    global _LOCKISH_RE
    if _LOCKISH_RE is None:
        import re

        _LOCKISH_RE = re.compile(r"mu|lock|cv|cond", re.IGNORECASE)
    return bool(_LOCKISH_RE.search(name))


def _acquires_lock(fn_node: ast.AST) -> bool:
    """Does this function body acquire something lock-shaped — a
    ``with`` over a lock-ish attribute/name (conditions included) or an
    explicit ``.acquire()`` call?"""
    for sub in ast.walk(fn_node):
        if isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    expr = expr.func
                last = None
                if isinstance(expr, ast.Attribute):
                    last = expr.attr
                elif isinstance(expr, ast.Name):
                    last = expr.id
                if last and _is_lockish_name(last):
                    return True
        elif isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
                return True
    return False


def _collect_guarded_decls(sf) -> list[tuple[str, dict]]:
    """(class name, {field: lockname}) for every class in the file with
    a literal ``_guarded_by_`` dict."""
    out = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_guarded_by_"
                and isinstance(stmt.value, ast.Dict)
            ):
                decl = {}
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    if (
                        isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Constant) and isinstance(v.value, str)
                    ):
                        decl[k.value] = v.value
                if decl:
                    out.append((node.name, decl))
    return out


def _guarded_mutations(cls_node: ast.ClassDef, fields):
    """(method node, field, kind, lineno) for every mutation of a
    declared field inside the class body.  ``kind`` is 'rebind' /
    'item' / 'call'."""
    hits = []

    def field_of(expr) -> str | None:
        # self.<field>  or  self.<field>[...]
        if isinstance(expr, ast.Subscript):
            expr = expr.value
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in fields
        ):
            return expr.attr
        return None

    for stmt in cls_node.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    f = field_of(tgt)
                    if f:
                        kind = "item" if isinstance(tgt, ast.Subscript) else "rebind"
                        hits.append((stmt, f, kind, sub.lineno))
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                f = field_of(sub.target)
                if f:
                    kind = "item" if isinstance(sub.target, ast.Subscript) else "rebind"
                    hits.append((stmt, f, kind, sub.lineno))
            elif isinstance(sub, ast.Delete):
                for tgt in sub.targets:
                    f = field_of(tgt)
                    if f:
                        hits.append((stmt, f, "item", sub.lineno))
            elif isinstance(sub, ast.Call):
                fn = sub.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _MUTATOR_METHODS
                ):
                    f = field_of(fn.value)
                    if f:
                        hits.append((stmt, f, "call", sub.lineno))
    return hits


def rule_guarded_fields(files, root: str) -> list[Finding]:
    graph = CallGraph(files)
    # Functions (by callgraph key) that acquire a lock-shaped object.
    locked: set[tuple] = set()
    for key, info in graph.funcs.items():
        if _acquires_lock(info.node):
            locked.add(key)
    # Reverse name-based edges: callee key -> caller keys.
    rev: dict[tuple, set] = {}
    for key, info in graph.funcs.items():
        for bare in info.calls:
            for callee in graph._resolve(info, bare):
                rev.setdefault(callee.key, set()).add(key)

    def any_locked_path(key: tuple) -> bool:
        """True when the method, or ANY transitive caller chain within
        the package, acquires a lock — or when a chain originates in a
        lifecycle method (`__init__`/`open`/...): the static analog of
        the runtime detector's init-phase single-thread exemption."""
        seen = {key}
        work = [key]
        while work:
            cur = work.pop()
            if cur in locked:
                return True
            info = graph.funcs.get(cur)
            if info is not None and cur != key and info.bare in _LIFECYCLE_EXEMPT:
                return True
            for caller in rev.get(cur, ()):
                if caller not in seen:
                    seen.add(caller)
                    work.append(caller)
        return False

    out: list[Finding] = []
    for sf in files:
        if sf.rel.startswith("analysis/"):
            continue
        decls = _collect_guarded_decls(sf)
        if not decls:
            continue
        by_name = {d[0]: d[1] for d in decls}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef) or node.name not in by_name:
                continue
            fields = by_name[node.name]
            for meth, field, kind, lineno in _guarded_mutations(node, fields):
                if meth.name in _LIFECYCLE_EXEMPT:
                    continue
                key = (sf.rel, f"{node.name}.{meth.name}")
                if key not in graph.funcs:
                    continue  # nested beyond the graph's scope
                if any_locked_path(key):
                    continue
                out.append(
                    Finding(
                        "guarded-fields", sf.rel, lineno,
                        f"{node.name}.{meth.name}",
                        f"`self.{field}` is declared guarded by "
                        f"`{fields[field]}` but this {kind} mutation has no "
                        "named-lock acquisition on any call path — take the "
                        "lock (or document why the site is exempt)",
                    )
                )
    return out


# -- 7. native-abi -----------------------------------------------------------
#
# The ctypes bridge is ~30 hand-declared signatures where drift is
# memory corruption, not an exception (the 22-argument pn_write_batch
# being the worst case).  analysis/abi.py reduces the extern "C"
# definitions, the argtypes/restype table, and the .so's export list to
# width-class tuples and fails on any missing symbol, arity mismatch,
# or width mismatch.  Findings anchor at the native.py declaration.

NATIVE_PY_REL = "native.py"
NATIVE_CPP_NAME = "pilosa_native.cpp"
NATIVE_SO_NAME = "libpilosa_native.so"


def rule_native_abi(files, root: str) -> list[Finding]:
    from pilosa_tpu.analysis import abi

    if not any(sf.rel == NATIVE_PY_REL for sf in files):
        return []  # tree without a native bridge (fixture packages)
    native_dir = os.path.join(os.path.dirname(os.path.abspath(root)), "native")
    cpp = os.path.join(native_dir, NATIVE_CPP_NAME)
    if not os.path.exists(cpp):
        return []  # source-only install: nothing to conform against
    so = os.path.join(native_dir, NATIVE_SO_NAME)
    out: list[Finding] = []
    for issue in abi.check_abi(cpp, os.path.join(root, NATIVE_PY_REL),
                               so_path=so):
        out.append(
            Finding(
                "native-abi", NATIVE_PY_REL, issue.line, issue.name,
                issue.message,
            )
        )
    return out


# -- 8/9. the GIL-dependence analyzer (generation 3) --------------------------
#
# Both hot lanes now do their heavy lifting GIL-released; the next
# multiplier is free-threaded or multi-worker serving (ROADMAP item 2),
# and that refactor is only safe once every place the code silently
# relies on the GIL is found.  Two rules split the hazard space:
#
# ``global-mutable-state`` — a module-level container binding that some
# function mutates at runtime has no lock contract at all: under the
# GIL each individual dict op is atomic, free-threaded it is a torn
# structure.  The fix the finding points at is the
# ``lockcheck.named_global`` registered-memo seam (bounded, lock-named,
# lockset-detector-fed), freezing the binding at import, or a reasoned
# suppression.
#
# ``check-then-act`` — a compound test-then-use on SHARED state
# (``if k in d: d[k]``, ``d.get(k)`` ... ``d[k] = ``, ``d.setdefault``,
# ``self.f += 1``) is atomic only because the GIL never switches
# threads mid-statement-pair.  Scope: functions reachable from the
# handler/lockstep/router entry points through a chain that never
# acquires a lock (the same name-based graph guarded-fields uses);
# receivers limited to ``self.<attr>`` and module-level globals (locals
# are thread-private by construction).
#
# Both rules share the documented over-approximation trades: name-based
# reachability errs toward MORE findings (absorbed by suppressions);
# the function-wide lock-acquisition check errs toward FEWER (a
# function locking ANYTHING anywhere exempts all its shapes — the same
# honesty trade as guarded-fields' lock-name shape matching).
# ``self.stat_*`` read-modify-writes are exempt by convention: the
# project's approximate counters lose increments under free threading,
# never correctness, and the convention is inventoried in
# DEVELOPMENT.md ("Free-threading readiness").

# Entry files whose every function is a seed: each is executed by a
# distinct thread population in a serving process (HTTP worker threads,
# lockstep rank threads, router probe/forward threads).
SERVING_ENTRY_FILES = ("server/handler.py", "parallel/service.py",
                       "replica/router.py")

_MUTABLE_FACTORIES = frozenset({
    "dict", "list", "set", "bytearray", "OrderedDict", "defaultdict",
    "deque", "Counter", "WeakKeyDictionary", "WeakValueDictionary",
})


def _serving_reachable(graph: CallGraph) -> set[tuple]:
    """Forward-reachable set from every serving entry function, with
    lifecycle methods excluded from the SEEDS (construction/open run
    once on one thread) but not from traversal."""
    seeds = []
    for rel in SERVING_ENTRY_FILES:
        seeds.extend(
            f for f in graph.seeds_matching(rel, "")
            if f.bare not in _LIFECYCLE_EXEMPT
        )
    if not seeds:
        return set()
    return graph.reachable_from(seeds)


def _module_mutable_bindings(sf) -> dict[str, int]:
    """Module-level ``name = <mutable container>`` bindings: dict/list/
    set displays and comprehensions, and the stdlib container factory
    calls.  A binding whose RHS is ``lockcheck.named_global(...)`` is
    the sanctioned seam and is not a container display, so it never
    becomes a candidate."""
    out: dict[str, int] = {}
    for stmt in sf.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            tgt, value = stmt.target, stmt.value
        else:
            continue
        if not isinstance(tgt, ast.Name):
            continue
        mutable = isinstance(
            value, (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp,
                    ast.SetComp)
        )
        if not mutable and isinstance(value, ast.Call):
            fn = value.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else ""
            )
            mutable = name in _MUTABLE_FACTORIES
        if mutable:
            out[tgt.id] = stmt.lineno
    return out


def _global_mutations(fn_node: ast.AST, names) -> list[tuple[str, str, int]]:
    """(name, kind, lineno) for every runtime mutation of a module-level
    binding inside one function body: item stores/deletes, in-place
    mutator calls, and ``global``-declared rebinds/augments."""
    declared_global: set[str] = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Global):
            declared_global.update(n for n in sub.names if n in names)
    hits: list[tuple[str, str, int]] = []

    def bare(expr) -> str | None:
        if isinstance(expr, ast.Subscript):
            expr = expr.value
        if isinstance(expr, ast.Name) and expr.id in names:
            return expr.id
        return None

    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Subscript):
                    n = bare(tgt)
                    if n:
                        hits.append((n, "item", sub.lineno))
                elif isinstance(tgt, ast.Name) and tgt.id in declared_global:
                    hits.append((tgt.id, "rebind", sub.lineno))
        elif isinstance(sub, ast.AugAssign):
            if isinstance(sub.target, ast.Subscript):
                n = bare(sub.target)
                if n:
                    hits.append((n, "item", sub.lineno))
            elif (isinstance(sub.target, ast.Name)
                  and sub.target.id in declared_global):
                hits.append((sub.target.id, "rebind", sub.lineno))
        elif isinstance(sub, ast.Delete):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Subscript):
                    n = bare(tgt)
                    if n:
                        hits.append((n, "item", sub.lineno))
        elif isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Attribute) and fn.attr in _MUTATOR_METHODS:
                n = bare(fn.value)
                if n:
                    hits.append((n, "call", sub.lineno))
    return hits


def rule_global_mutable_state(files, root: str) -> list[Finding]:
    graph = CallGraph(files)
    reachable = _serving_reachable(graph)
    out: list[Finding] = []
    for sf in files:
        if sf.rel.startswith("analysis/"):
            continue
        bindings = _module_mutable_bindings(sf)
        if not bindings:
            continue
        # name -> first serving-reachable mutation (scope, kind, line)
        witness: dict[str, tuple[str, str, int]] = {}
        for key, info in sorted(graph.funcs.items()):
            if info.rel != sf.rel or key not in reachable:
                continue
            for name, kind, lineno in _global_mutations(info.node, bindings):
                if name not in witness:
                    witness[name] = (info.scope, kind, lineno)
        for name in sorted(witness):
            scope, kind, lineno = witness[name]
            out.append(
                Finding(
                    "global-mutable-state", sf.rel, bindings[name], "<module>",
                    f"module-level mutable `{name}` is mutated at runtime "
                    f"({kind} in {scope}:{lineno}, serving-reachable) with no "
                    "lock contract — a free-threaded host tears it: freeze "
                    "it at import, register it via lockcheck.named_global("
                    "...), or tag why it is safe",
                )
            )
    return out


class _CheckThenActVisitor(ast.NodeVisitor):
    """Scans ONE function body for compound test-then-use shapes on
    shared receivers (``self.<attr>`` / module globals).  Nested defs
    are their own call-graph nodes; their hits are deduped by line."""

    def __init__(self, rel: str, scope: str, module_names, out: list):
        self.rel = rel
        self.scope = scope
        self.module_names = module_names
        self.out = out
        self._gets: dict[str, int] = {}       # recv text -> first .get line
        self._stores: dict[str, int] = {}     # recv text -> first d[k]= line

    def _recv(self, expr) -> str | None:
        """Shared-receiver filter: self.<attr> or a module-level name.
        Lock-ish receivers are the serialization mechanism itself."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and not _is_lockish_name(expr.attr)
        ):
            return f"self.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in self.module_names:
            return expr.id
        return None

    def _flag(self, lineno: int, msg: str) -> None:
        self.out.append(
            Finding("check-then-act", self.rel, lineno, self.scope, msg)
        )

    def visit_If(self, node: ast.If) -> None:
        test = node.test
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            test = test.operand
        if (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.In, ast.NotIn))
        ):
            recv = self._recv(test.comparators[0])
            if recv is not None:
                # Either branch acting on the tested receiver is the
                # race: `if k in d: use d[k]` reads an entry a peer can
                # delete; `if k not in d: d[k] = ...` double-fills.
                hit = False
                for stmt in node.body + node.orelse:
                    for sub in ast.walk(stmt):
                        if (
                            isinstance(sub, ast.Subscript)
                            and self._recv(sub.value) == recv
                        ):
                            hit = True
                            break
                    if hit:
                        break
                if hit:
                    self._flag(
                        node.lineno,
                        f"membership test on `{recv}` guards a subscript "
                        "use — the entry can appear/vanish between test "
                        "and use without the GIL; hold a named lock "
                        "across the pair (or use one atomic operation)",
                    )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv = self._recv(fn.value)
            if recv is not None:
                if fn.attr == "setdefault":
                    self._flag(
                        node.lineno,
                        f"`{recv}.setdefault(...)` on shared state — the "
                        "default may be constructed and inserted twice "
                        "free-threaded; hold a named lock across the "
                        "lookup-or-create",
                    )
                elif fn.attr == "get":
                    self._gets.setdefault(recv, node.lineno)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                recv = self._recv(tgt.value)
                if recv is not None:
                    self._stores.setdefault(recv, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        tgt = node.target
        if isinstance(tgt, ast.Subscript):
            recv = self._recv(tgt.value)
            if recv is not None:
                self._stores.setdefault(recv, node.lineno)
        elif isinstance(tgt, ast.Attribute):
            recv = self._recv(tgt)
            # self.stat_* counters are approximate by convention
            # (inventoried in DEVELOPMENT.md): a torn increment loses a
            # count, never correctness.
            if recv is not None and not tgt.attr.startswith("stat"):
                self._flag(
                    node.lineno,
                    f"unlocked read-modify-write of shared `{recv}` — the "
                    "load and store can interleave with another thread's "
                    "free-threaded; hold a named lock (approximate stat_* "
                    "counters are the documented exception)",
                )
        self.generic_visit(node)

    def finish(self) -> None:
        """Pair the recorded .get() probes with item stores on the same
        receiver: the with_tags-style lazy-singleton shape."""
        for recv, gline in sorted(self._gets.items()):
            if recv in self._stores:
                self._flag(
                    gline,
                    f"`{recv}.get(...)` at line {gline} paired with "
                    f"`{recv}[...] = ` at line {self._stores[recv]} — the "
                    "get-then-store races free-threaded (two threads both "
                    "miss, both store); hold a named lock across the pair",
                )


def rule_check_then_act(files, root: str) -> list[Finding]:
    graph = CallGraph(files)
    reachable = _serving_reachable(graph)
    out: list[Finding] = []
    for sf in files:
        if sf.rel.startswith("analysis/"):
            continue
        module_names = _module_mutable_bindings(sf)
        raw: list[Finding] = []
        seen_lines: set[int] = set()
        for key, info in sorted(graph.funcs.items()):
            if info.rel != sf.rel or key not in reachable:
                continue
            if info.bare in _LIFECYCLE_EXEMPT:
                continue
            if _acquires_lock(info.node):
                # The function serializes SOMETHING itself; its compound
                # shapes are assumed covered (documented fewer-findings
                # trade — same shape-matching honesty as guarded-fields).
                continue
            v = _CheckThenActVisitor(sf.rel, info.scope, module_names, raw)
            v.visit(info.node)
            v.finish()
        for f in raw:
            # Nested defs re-walk enclosing statements: keep the first
            # finding per line.
            if f.line not in seen_lines:
                seen_lines.add(f.line)
                out.append(f)
    return out


# -- 10. env-knob-outside-config (generation 4) -------------------------------
#
# The knob-plumbing contract (planner PR): every tuning knob that
# ``config.py`` owns flows CLI > env > config file > default through a
# Config field and arrives at its consumer as a constructor argument.
# A raw ``os.environ`` read of an owned knob anywhere else creates a
# second, precedence-free spelling that silently shadows the config
# file — exactly the drift the unification removed.  The owned set is
# DERIVED from config.py's own env reads (no second list to maintain):
# add a knob to ``Config.apply_env`` and every stray read of it
# becomes a finding.  Deliberate exceptions carry suppressions: the
# executor's deprecated direct-construction fallbacks, and the
# lockstep service's rank-process reads (ranks inherit the launcher's
# env wholesale; no config file is plumbed to them).  Gate/diagnostic
# variables config.py does not read (PILOSA_TPU_LOCK_CHECK,
# PILOSA_TPU_FAULT_SPEC, ...) are out of scope by construction.

CONFIG_REL = "config.py"
_ENV_GET_CALLS = ("os.getenv", "os.environ.get")


def _env_read_name(node: ast.AST) -> str | None:
    """The constant env-var name a node reads, or None: matches
    ``os.getenv("X")`` / ``os.environ.get("X"[, d])`` /
    ``os.environ["X"]``."""
    if isinstance(node, ast.Call):
        if _unparse(node.func) in _ENV_GET_CALLS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
    elif isinstance(node, ast.Subscript):
        if _unparse(node.value) == "os.environ":
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                return sl.value
    return None


def _config_owned_knobs(sf) -> set[str]:
    """Constant PILOSA_TPU_* names config.py consumes.  Config reads
    env through ``apply_env``'s injected mapping (``env["X"]``,
    ``"X" in env``, ``env.get("X")``) as well as ``os.environ``
    directly; match all four shapes."""

    def const_str(expr) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        return None

    out: set[str] = set()
    for node in ast.walk(sf.tree):
        name = _env_read_name(node)
        if name is None:
            if isinstance(node, ast.Subscript) and _unparse(node.value) == "env":
                name = const_str(node.slice)
            elif (
                isinstance(node, ast.Call)
                and _unparse(node.func) == "env.get"
                and node.args
            ):
                name = const_str(node.args[0])
            elif (
                isinstance(node, ast.Compare)
                and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and _unparse(node.comparators[0]) == "env"
            ):
                name = const_str(node.left)
        if name and name.startswith("PILOSA_TPU_"):
            out.add(name)
    return out


def rule_env_knob_outside_config(files, root: str) -> list[Finding]:
    owned: set[str] = set()
    for sf in files:
        if sf.rel == CONFIG_REL:
            owned = _config_owned_knobs(sf)
            break
    if not owned:
        return []  # tree without a config module (fixture packages)
    out: list[Finding] = []
    for sf in files:
        if sf.rel == CONFIG_REL or sf.rel.startswith("analysis/"):
            continue

        from pilosa_tpu.analysis.engine import ScopedVisitor

        class V(ScopedVisitor):
            def _check(inner, node):
                name = _env_read_name(node)
                if name in owned:
                    out.append(
                        Finding(
                            "env-knob-outside-config", sf.rel, node.lineno,
                            inner.scope_name(),
                            f"raw environment read of `{name}` — a "
                            "config-owned tuning knob (CLI > env > config "
                            "file > default); take it as a constructor/"
                            "Config value, or tag the deprecated fallback",
                        )
                    )

            def visit_Call(inner, node):
                inner._check(node)
                inner.generic_visit(node)

            def visit_Subscript(inner, node):
                inner._check(node)
                inner.generic_visit(node)

        V().visit(sf.tree)
    return out
