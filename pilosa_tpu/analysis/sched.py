"""Deterministic interleaving explorer: cooperative schedule control.

The lock-order checker (PR 8) and the lockset race detector (PR 11)
only observe the interleavings a test run happens to produce — a
reordering bug in the sequencer/WAL/catch-up protocol can hide for
months behind a scheduler that never preempts at the wrong instruction.
This module removes the luck: scenario threads run REAL project code,
but every interesting step — a named-lock acquisition, a condition
wait, a guarded-field write, a patched blocking call — is a YIELD
POINT where control returns to a single driver thread, which then
decides (deterministically) who runs next.  Exactly one scenario
thread executes at any moment, so the code between two yield points is
atomic by construction, and an execution is fully described by the
sequence of thread choices — a SCHEDULE.

Exploration is exhaustive under an ITERATIVE PREEMPTION BOUND (the
CHESS discipline: most concurrency bugs need only 1-2 preemptions) with
a CONFLICT-BASED partial-order reduction: at a scheduling point, an
alternative thread is only worth branching to when its pending
operation CONFLICTS with the one actually executed — same lock name,
same condition, same declared guarded field, same blocking kind.
Independent steps commute, so reordering them reaches an equivalent
state.  (This prunes by the CURRENTLY pending operations, not by
future ones — a deliberate under-approximation, documented in
DEVELOPMENT.md; the seeded-schedule fuzzer covers orderings beyond the
reduced set.)

Every execution's schedule serializes to a compact string
(``"0x3,1x2,0"`` — run-length thread choices, the same replay-a-string
spirit as the ``PILOSA_TPU_FAULT_SPEC`` grammar) and
:func:`replay` re-runs that exact interleaving in one shot, so a
failing schedule found by CI reproduces on the first try at a desk.

Yield points hook the existing lockcheck seams
(:func:`pilosa_tpu.analysis.lockcheck.set_sched`):

- ``named_lock`` / ``named_rlock`` / ``named_condition`` factories
  return :class:`SchedLock` / :class:`SchedRLock` /
  :class:`SchedCondition` while a run is active — a blocking acquire
  yields and is granted only when the lock is free (so real primitives
  never block and a cyclic wait shows up as an explicit DEADLOCK
  outcome with the schedule that produced it);
- guarded-class ``__setattr__`` yields BEFORE the store (the
  interleaving that loses an unlocked read-modify-write needs a switch
  between the read and the write);
- the blocking-call patches (``os.fsync`` et al.) yield at the
  crossing.

Outcomes per execution: clean, a thread exception, a scenario
invariant failure (``check()`` raised), a deadlock (no enabled
thread), a protocol-trace conformance failure (analysis/spec.py), or a
step-limit truncation (counted, never silently dropped).  Determinism
is a hard contract: same scenario + same bound => identical schedule
count and identical outcome set, asserted in tests.

NOTE the raw ``threading`` primitives below are the scheduler's OWN
machinery (baton semaphores, the fall-through inner locks) and must be
invisible to the lock checker by construction — this file is exempted
from the lock-discipline rule exactly like lockcheck.py itself.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from pilosa_tpu.analysis import lockcheck
from pilosa_tpu.stats import NOP_STATS

# Hard caps: an execution that exceeds MAX_STEPS is recorded as
# truncated (deterministically — same cap, same truncation), and an
# exploration that would exceed max_schedules stops with the count so
# far.  Both surface in the result rather than hanging tier-1.
DEFAULT_MAX_STEPS = 4000
DEFAULT_MAX_SCHEDULES = 4000


class _SchedAbort(BaseException):
    """Raised inside a scenario thread to unwind it during run
    abandonment (deadlock/truncation teardown).  BaseException so
    ordinary ``except Exception`` recovery code cannot swallow it."""


class Op:
    """One pending operation at a yield point.  ``key`` is the stable
    resource label (lock/cv name, ``Class.field``, blocking kind) the
    conflict-based reduction compares."""

    __slots__ = ("kind", "key", "lock", "cv", "waiter", "timeout")

    def __init__(self, kind: str, key: str, lock=None, cv=None, waiter=None,
                 timeout=None):
        self.kind = kind  # start|acquire|tryacquire|wait|field|block
        self.key = key
        self.lock = lock
        self.cv = cv
        self.waiter = waiter
        self.timeout = timeout

    def label(self) -> str:
        return f"{self.kind}:{self.key}"


def _conflicts(a: Op, b: Op) -> bool:
    """Two pending ops conflict when they touch the same resource —
    the only case where executing them in the other order can reach a
    different state (lock/cv names share one namespace with the
    conditions built over them; field keys are ``Class.field``).  A
    thread's START op is a wildcard: its first segment is opaque code
    whose reads the instrumentation cannot see, so its placement is
    never provably independent of anything."""
    if a.kind == "start" or b.kind == "start":
        return True
    return a.key == b.key


class _Waiter:
    __slots__ = ("thread", "notified")

    def __init__(self, thread):
        self.thread = thread
        self.notified = False


class _SThread:
    """One scenario thread under schedule control."""

    __slots__ = ("index", "fn", "thread", "sem", "pending", "done", "exc",
                 "abort")

    def __init__(self, index: int, fn: Callable[[], None]):
        self.index = index
        self.fn = fn
        self.sem = threading.Semaphore(0)
        self.pending: Op = Op("start", f"t{index}")
        self.done = False
        self.exc: Optional[BaseException] = None
        self.abort = False
        self.thread: Optional[threading.Thread] = None


# The active run (at most one per process — explorations are
# sequential) — consulted by the primitives and the lockcheck seam.
_ACTIVE: Optional["_Run"] = None


class _Hook:
    """The object installed via lockcheck.set_sched: factory + yield
    seams.  Primitives built under an active run keep working after it
    ends (they fall through to their real inner primitive when the
    calling thread is not a scheduled scenario thread)."""

    def make_lock(self, name: str):
        return SchedLock(name)

    def make_rlock(self, name: str):
        return SchedRLock(name)

    def make_condition(self, name: str, lock=None):
        if lock is not None and not isinstance(lock, SchedLock):
            return threading.Condition(lock)
        return SchedCondition(name, lock)

    def field_write(self, obj, cls_name: str, field: str) -> None:
        run, t = _current()
        if t is not None:
            run._yield(t, Op("field", f"{cls_name}.{field}"))

    def blocking_point(self, kind: str) -> None:
        run, t = _current()
        if t is not None:
            run._yield(t, Op("block", kind))


_HOOK = _Hook()


def _current():
    """(run, scenario-thread record) for the calling thread, or
    (None, None) when it is not under schedule control."""
    run = _ACTIVE
    if run is None:
        return None, None
    return run, run.by_ident.get(threading.get_ident())


class SchedLock:
    """Lock under exploration control.  A scheduled thread's blocking
    acquire yields and is granted only when the lock is free, so the
    inner primitive never blocks; threads outside the run fall through
    to the real lock."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        # A plain Lock suffices even for SchedRLock: recursion is
        # tracked by (owner, depth) above it — the inner primitive is
        # only taken on first acquisition and released at depth zero.
        self._inner = threading.Lock()
        self.owner: Optional[_SThread] = None
        self.depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        run, t = _current()
        if t is None:
            return self._inner.acquire(blocking, timeout)
        if self.owner is t and self._reentrant:
            self.depth += 1
            return True
        if not blocking or timeout == 0:
            # Try-acquire is still a scheduling point (always enabled:
            # it can fail without blocking), then an atomic test.
            run._yield(t, Op("tryacquire", self.name, lock=self))
            if self.owner is not None:
                return False
            self._take(t)
            return True
        run._yield(t, Op("acquire", self.name, lock=self))
        # The driver grants an acquire only when the lock is free.
        self._take(t)
        return True

    def _take(self, t: _SThread) -> None:
        self.owner = t
        self.depth = 1
        self._inner.acquire()

    def release(self) -> None:
        run, t = _current()
        if t is None:
            self._inner.release()
            return
        if self.owner is not t:
            raise RuntimeError(f"release of {self.name} by non-owner")
        self.depth -= 1
        if self.depth == 0:
            self.owner = None
            self._inner.release()

    def locked(self) -> bool:
        return self.owner is not None or self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<SchedLock {self.name} owner={getattr(self.owner, 'index', None)}>"


class SchedRLock(SchedLock):
    _reentrant = True


class SchedCondition:
    """Condition variable under exploration control.  wait() fully
    releases the lock, parks the thread (enabled again on notify, or —
    for a TIMED wait — schedulable as a timeout fire), then re-acquires
    through the normal acquire gate.  notify()/notify_all() are
    non-yielding (they happen inside the notifier's step)."""

    def __init__(self, name: str, lock: Optional[SchedLock] = None):
        self.name = name
        self._lock = lock if lock is not None else SchedLock(name)
        self._waiters: list[_Waiter] = []  # FIFO

    # Context-manager / lock protocol delegates.
    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        return self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        run, t = _current()
        if t is None:
            raise RuntimeError(
                f"SchedCondition {self.name}: wait() outside an exploration "
                "run (scenario objects must not outlive their run)"
            )
        if self._lock.owner is not t:
            raise RuntimeError(f"wait on {self.name} without owning its lock")
        depth = self._lock.depth
        # Fully release (mirrors CheckedRLock._release_save).
        self._lock.owner = None
        self._lock.depth = 0
        self._lock._inner.release()
        w = _Waiter(t)
        self._waiters.append(w)
        try:
            run._yield(t, Op("wait", self.name, cv=self, waiter=w,
                             timeout=timeout))
        finally:
            if w in self._waiters:
                self._waiters.remove(w)
        notified = w.notified
        run._yield(t, Op("acquire", self.name, lock=self._lock))
        self._lock._take(t)
        self._lock.depth = depth
        return notified

    def notify(self, n: int = 1) -> None:
        for w in self._waiters[:n]:
            w.notified = True

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


# -- one execution ----------------------------------------------------------


class _StepMeta:
    """Per-step record the branch generator consumes."""

    __slots__ = ("enabled", "ops", "cur", "chosen")

    def __init__(self, enabled, ops, cur, chosen):
        self.enabled = enabled  # tuple of enabled thread indices (sorted)
        self.ops = ops  # {index: Op} pending ops of the enabled threads
        self.cur = cur  # index of the previously-run thread (or None)
        self.chosen = chosen


class RunResult:
    __slots__ = ("seq", "meta", "deadlock", "truncated", "exceptions",
                 "diverged", "blocked")

    def __init__(self):
        self.seq: list[int] = []
        self.meta: list[_StepMeta] = []
        self.deadlock = False
        self.truncated = False
        self.diverged = False
        self.exceptions: list[tuple[int, BaseException]] = []
        self.blocked: list[str] = []  # "tN on op" captured at deadlock


class _Run:
    """One execution of a scenario's threads under a decision prefix."""

    def __init__(self, fns, max_steps: int = DEFAULT_MAX_STEPS):
        self.threads = [_SThread(i, fn) for i, fn in enumerate(fns)]
        self.by_ident: dict[int, _SThread] = {}
        self.baton = threading.Semaphore(0)
        self.max_steps = max_steps

    # -- scenario-thread side ---------------------------------------------

    def _thread_main(self, t: _SThread) -> None:
        t.sem.acquire()  # the start grant
        try:
            if not t.abort:
                t.fn()
        except _SchedAbort:
            pass
        except BaseException as e:  # noqa: BLE001 — recorded as the outcome
            t.exc = e
        finally:
            t.done = True
            self.baton.release()

    def _yield(self, t: _SThread, op: Op) -> None:
        t.pending = op
        self.baton.release()
        t.sem.acquire()
        if t.abort:
            raise _SchedAbort()

    # -- driver side -------------------------------------------------------

    def _enabled(self, t: _SThread) -> bool:
        op = t.pending
        if op.kind == "acquire":
            lk = op.lock
            return lk.owner is None or (lk.owner is t and lk._reentrant)
        if op.kind == "wait":
            return op.waiter.notified or op.timeout is not None
        return True  # start / tryacquire / field / block

    def _default_choice(self, cur: Optional[int], enabled: list[_SThread]):
        """Non-preemptive completion policy: keep running the current
        thread — unless its only move is firing a wait timeout while
        another thread can make real progress (the group-commit
        follower's 50 ms poll would otherwise spin the execution into
        the step cap)."""

        def is_idle_timeout(t: _SThread) -> bool:
            return t.pending.kind == "wait" and not t.pending.waiter.notified

        by_index = {t.index: t for t in enabled}
        if cur is not None and cur in by_index:
            t = by_index[cur]
            if not (is_idle_timeout(t) and len(enabled) > 1):
                return t
        progress = [t for t in enabled if not is_idle_timeout(t)]
        return (progress or enabled)[0]

    def run(self, decisions: list[int]) -> RunResult:
        global _ACTIVE
        res = RunResult()
        for t in self.threads:
            t.thread = threading.Thread(
                target=self._thread_main, args=(t,),
                name=f"sched-t{t.index}", daemon=True,
            )
        _ACTIVE = self
        try:
            for t in self.threads:
                t.thread.start()
                self.by_ident[t.thread.ident] = t
            cur: Optional[int] = None
            step = 0
            while True:
                alive = [t for t in self.threads if not t.done]
                if not alive:
                    break
                enabled = sorted(
                    (t for t in alive if self._enabled(t)),
                    key=lambda t: t.index,
                )
                if not enabled:
                    res.deadlock = True
                    res.blocked = [
                        f"t{t.index} on {t.pending.label()}" for t in alive
                    ]
                    break
                if step >= self.max_steps:
                    res.truncated = True
                    break
                if step < len(decisions):
                    want = decisions[step]
                    chosen = next((t for t in enabled if t.index == want), None)
                    if chosen is None:
                        res.diverged = True
                        break
                else:
                    chosen = self._default_choice(cur, enabled)
                res.meta.append(
                    _StepMeta(
                        tuple(t.index for t in enabled),
                        {t.index: t.pending for t in enabled},
                        cur,
                        chosen.index,
                    )
                )
                res.seq.append(chosen.index)
                chosen.sem.release()
                self.baton.acquire()
                cur = chosen.index
                step += 1
        finally:
            self._teardown()
            _ACTIVE = None
        for t in self.threads:
            if t.exc is not None:
                res.exceptions.append((t.index, t.exc))
        return res

    def _teardown(self) -> None:
        """Unwind any still-parked threads (deadlock/truncation/diverge
        paths).  Each aborted thread raises _SchedAbort from its pending
        yield; a thread that refuses to die within the bound is leaked
        as a daemon (its scenario objects are execution-local, so it
        cannot perturb later runs)."""
        for _ in range(64):
            live = [t for t in self.threads if not t.done]
            if not live:
                break
            for t in live:
                t.abort = True
                t.sem.release()
            self.baton.acquire(timeout=0.2)
        for t in self.threads:
            if t.thread is not None:
                t.thread.join(timeout=1.0)


# -- schedule strings --------------------------------------------------------


def format_schedule(seq: list[int]) -> str:
    """Run-length encode a thread-choice sequence: [0,0,0,1,1,0] ->
    "0x3,1x2,0"."""
    out = []
    i = 0
    while i < len(seq):
        j = i
        while j < len(seq) and seq[j] == seq[i]:
            j += 1
        n = j - i
        out.append(f"{seq[i]}x{n}" if n > 1 else f"{seq[i]}")
        i = j
    return ",".join(out)


def parse_schedule(s: str) -> list[int]:
    out: list[int] = []
    for tok in s.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "x" in tok:
            tid, _, n = tok.partition("x")
            out.extend([int(tid)] * int(n))
        else:
            out.append(int(tok))
    return out


# -- scenarios ---------------------------------------------------------------


class Scenario:
    """One explorable concurrency scenario.

    ``build()`` returns a fresh context object exposing:

    - ``threads``: the list of zero-arg callables to run under schedule
      control (real project code; everything they lock must be built
      inside ``build`` so the factories hand out Sched primitives);
    - ``check()``: post-execution invariants — raises AssertionError on
      a violation (called only for executions that ran to completion);
    - optionally ``close()``: resource teardown (tmp dirs), always
      called.

    ``trace_check=True`` additionally runs the replica-protocol
    trace-conformance checker (analysis/spec.py) over the events each
    execution emitted.  ``known_bug=True`` marks a seeded bug fixture:
    the live-tree gate skips it, and a dedicated test asserts the
    explorer FINDS it and that the printed schedule replays it.
    """

    def __init__(self, name: str, build: Callable, description: str = "",
                 known_bug: bool = False, trace_check: bool = False,
                 bound: int = 2, max_steps: int = DEFAULT_MAX_STEPS,
                 max_schedules: int = DEFAULT_MAX_SCHEDULES):
        self.name = name
        self.build = build
        self.description = description or (build.__doc__ or "").strip()
        self.known_bug = known_bug
        self.trace_check = trace_check
        self.bound = bound
        self.max_steps = max_steps
        self.max_schedules = max_schedules


class Outcome:
    """One failing execution: what went wrong and the schedule string
    that replays it."""

    __slots__ = ("kind", "schedule", "detail")

    def __init__(self, kind: str, schedule: str, detail: str):
        self.kind = kind  # exception|check|deadlock|trace
        self.schedule = schedule
        self.detail = detail

    def describe(self) -> str:
        return (
            f"[{self.kind}] schedule {self.schedule or '<empty>'}\n"
            f"  {self.detail}"
        )


class ExploreResult:
    __slots__ = ("scenario", "bound", "schedules", "truncated", "outcomes")

    def __init__(self, scenario: str, bound: int):
        self.scenario = scenario
        self.bound = bound
        self.schedules = 0
        self.truncated = 0
        self.outcomes: list[Outcome] = []

    @property
    def ok(self) -> bool:
        return not self.outcomes

    def describe(self) -> str:
        head = (
            f"{self.scenario}: {self.schedules} schedule(s) at preemption "
            f"bound {self.bound}, {self.truncated} truncated, "
            f"{len(self.outcomes)} violation(s)"
        )
        if not self.outcomes:
            return head
        return head + "\n" + "\n".join(o.describe() for o in self.outcomes)


def _execute(scenario: Scenario, decisions: list[int],
             max_steps: int) -> tuple[RunResult, list[Outcome]]:
    """Run the scenario once under a decision prefix; returns the run
    record and any failure outcomes."""
    from pilosa_tpu.analysis import spec

    lockcheck.set_sched(_HOOK)
    lockcheck.sched_instrument()
    events = spec.install_collector() if scenario.trace_check else None
    ctx = None
    try:
        ctx = scenario.build()
        run = _Run(list(ctx.threads), max_steps=max_steps)
        res = run.run(decisions)
        outcomes: list[Outcome] = []
        sched_str = format_schedule(res.seq)
        for idx, exc in res.exceptions:
            outcomes.append(
                Outcome("exception", sched_str,
                        f"thread {idx}: {type(exc).__name__}: {exc}")
            )
        if res.deadlock:
            outcomes.append(
                Outcome("deadlock", sched_str,
                        "no enabled thread: " + ", ".join(res.blocked))
            )
        if not res.deadlock and not res.truncated and not res.diverged \
                and not res.exceptions:
            try:
                ctx.check()
            except AssertionError as e:
                outcomes.append(Outcome("check", sched_str, str(e)))
        if events is not None:
            for v in spec.check_trace(events):
                outcomes.append(Outcome("trace", sched_str, v))
        return res, outcomes
    finally:
        if ctx is not None and hasattr(ctx, "close"):
            ctx.close()
        if events is not None:
            spec.uninstall_collector()
        lockcheck.set_sched(None)
        lockcheck.sched_uninstrument()


def _preemptions(seq: list[int], meta: list[_StepMeta]) -> list[int]:
    """Cumulative preemption count before each step: step i preempted
    when the previously-running thread was still enabled but a
    different one was chosen."""
    used = 0
    out = []
    for i, m in enumerate(meta):
        out.append(used)
        if m.cur is not None and m.cur in m.enabled and seq[i] != m.cur:
            used += 1
    return out


def explore(scenario: Scenario, bound: Optional[int] = None,
            max_schedules: Optional[int] = None,
            max_steps: Optional[int] = None,
            stats=None) -> ExploreResult:
    """Exhaustively explore the scenario's interleavings with at most
    ``bound`` preemptions, pruned by the conflict-based partial-order
    reduction.  Deterministic: same scenario + bound => same schedule
    count and outcomes."""
    bound = scenario.bound if bound is None else bound
    max_schedules = scenario.max_schedules if max_schedules is None else max_schedules
    max_steps = scenario.max_steps if max_steps is None else max_steps
    stats = stats if stats is not None else NOP_STATS
    result = ExploreResult(scenario.name, bound)
    seen_prefixes: set[tuple[int, ...]] = set()
    seen_seqs: set[tuple[int, ...]] = set()
    stack: list[list[int]] = [[]]
    seen_prefixes.add(())
    while stack:
        if result.schedules >= max_schedules:
            result.truncated += 1
            break
        prefix = stack.pop()
        res, outcomes = _execute(scenario, prefix, max_steps)
        if res.diverged:
            continue  # a sibling branch changed enabledness; prefix dead
        seq = tuple(res.seq)
        if seq in seen_seqs:
            continue
        seen_seqs.add(seq)
        result.schedules += 1
        if res.truncated:
            result.truncated += 1
        result.outcomes.extend(outcomes)
        # Branch generation: at every step, consider the enabled
        # alternatives whose pending op CONFLICTS with the op of the
        # thread actually run; a switch away from a still-enabled
        # current thread costs one unit of the preemption budget.
        pre = _preemptions(res.seq, res.meta)
        for i, m in enumerate(res.meta):
            chosen_op = m.ops[m.chosen]
            for alt in m.enabled:
                if alt == m.chosen:
                    continue
                preemptive = m.cur is not None and m.cur in m.enabled \
                    and alt != m.cur
                if pre[i] + (1 if preemptive else 0) > bound:
                    continue
                if not _conflicts(m.ops[alt], chosen_op):
                    continue
                cand = list(res.seq[:i]) + [alt]
                key = tuple(cand)
                if key not in seen_prefixes:
                    seen_prefixes.add(key)
                    stack.append(cand)
        # LIFO order is deterministic because alternatives were pushed
        # in sorted (step, thread) order within each execution.
    stats.count("analysis.sched.schedules", result.schedules)
    if result.truncated:
        stats.count("analysis.sched.truncated", result.truncated)
    if result.outcomes:
        stats.count("analysis.sched.violations", len(result.outcomes))
    return result


def replay(scenario: Scenario, schedule: str,
           max_steps: Optional[int] = None, stats=None) -> list[Outcome]:
    """Re-run ONE schedule (a string printed by a failing exploration)
    and return its outcomes — the deterministic repro lane."""
    stats = stats if stats is not None else NOP_STATS
    decisions = parse_schedule(schedule)
    res, outcomes = _execute(
        scenario, decisions,
        scenario.max_steps if max_steps is None else max_steps,
    )
    stats.count("analysis.sched.replays")
    if res.diverged:
        outcomes.append(
            Outcome(
                "exception", schedule,
                "schedule diverged: a prescribed thread was not enabled at "
                "its step (stale schedule string, or the scenario changed)",
            )
        )
    return outcomes


def fuzz(scenario: Scenario, seed: int, runs: int = 16,
         max_steps: Optional[int] = None, stats=None) -> ExploreResult:
    """Seeded random-schedule fuzzing BEYOND the exhaustive preemption
    bound: each run draws uniformly among the enabled threads at every
    step.  Deterministic per (scenario, seed, runs) — failures print
    the same replayable schedule strings as explore()."""
    import random

    stats = stats if stats is not None else NOP_STATS
    rng = random.Random(seed)
    result = ExploreResult(scenario.name, -1)
    max_steps = scenario.max_steps if max_steps is None else max_steps
    for _ in range(runs):
        # Pre-draw a long random decision tape; _execute maps each
        # entry onto the enabled set at that step via modulo, so the
        # tape is schedule-complete for any enabledness pattern.
        tape = [rng.randrange(1 << 30) for _ in range(max_steps)]
        res, outcomes = _execute_random(scenario, tape, max_steps)
        result.schedules += 1
        if res.truncated:
            result.truncated += 1
        result.outcomes.extend(outcomes)
    stats.count("analysis.sched.fuzz_runs", result.schedules)
    if result.outcomes:
        stats.count("analysis.sched.violations", len(result.outcomes))
    return result


def _execute_random(scenario: Scenario, tape: list[int], max_steps: int):
    """One fuzz execution: the pre-drawn tape indexes into the enabled
    set at each step.  The EXECUTED sequence is recorded, so a failure
    replays through the standard schedule-string lane."""
    from pilosa_tpu.analysis import spec

    lockcheck.set_sched(_HOOK)
    lockcheck.sched_instrument()
    events = spec.install_collector() if scenario.trace_check else None
    ctx = None
    try:
        ctx = scenario.build()
        run = _Run(list(ctx.threads), max_steps=max_steps)
        # Random choice = a decision list resolved step by step: drive
        # the run manually with a choice function.
        res = _drive_random(run, tape, max_steps)
        outcomes: list[Outcome] = []
        sched_str = format_schedule(res.seq)
        for idx, exc in res.exceptions:
            outcomes.append(
                Outcome("exception", sched_str,
                        f"thread {idx}: {type(exc).__name__}: {exc}")
            )
        if res.deadlock:
            outcomes.append(
                Outcome("deadlock", sched_str,
                        "no enabled thread: " + ", ".join(res.blocked))
            )
        if not (res.deadlock or res.truncated or res.exceptions):
            try:
                ctx.check()
            except AssertionError as e:
                outcomes.append(Outcome("check", sched_str, str(e)))
        if events is not None:
            for v in spec.check_trace(events):
                outcomes.append(Outcome("trace", sched_str, v))
        return res, outcomes
    finally:
        if ctx is not None and hasattr(ctx, "close"):
            ctx.close()
        if events is not None:
            spec.uninstall_collector()
        lockcheck.set_sched(None)
        lockcheck.sched_uninstrument()


def _drive_random(run: _Run, tape: list[int], max_steps: int) -> RunResult:
    global _ACTIVE
    res = RunResult()
    for t in run.threads:
        t.thread = threading.Thread(
            target=run._thread_main, args=(t,),
            name=f"sched-t{t.index}", daemon=True,
        )
    _ACTIVE = run
    try:
        for t in run.threads:
            t.thread.start()
            run.by_ident[t.thread.ident] = t
        step = 0
        while True:
            alive = [t for t in run.threads if not t.done]
            if not alive:
                break
            enabled = sorted(
                (t for t in alive if run._enabled(t)), key=lambda t: t.index
            )
            if not enabled:
                res.deadlock = True
                res.blocked = [
                    f"t{t.index} on {t.pending.label()}" for t in alive
                ]
                break
            if step >= max_steps:
                res.truncated = True
                break
            chosen = enabled[tape[step] % len(enabled)]
            res.seq.append(chosen.index)
            chosen.sem.release()
            run.baton.acquire()
            step += 1
    finally:
        run._teardown()
        _ACTIVE = None
    for t in run.threads:
        if t.exc is not None:
            res.exceptions.append((t.index, t.exc))
    return res
