"""Native-boundary ABI conformance: C++ ``extern "C"`` declarations vs
the ctypes ``argtypes``/``restype`` table in ``pilosa_tpu/native.py`` vs
the built ``.so``'s exported symbols.

The native bridge is ~30 hand-declared signatures — including the
22-argument ``pn_write_batch`` — where a silent drift between the C
definition and the Python declaration is not an exception but memory
corruption (ctypes marshals whatever widths it was told).  This module
reduces every signature to a WIDTH-CLASS tuple and compares:

- ``ptr``  — any pointer (``const char*``, ``uint64_t*``, ``c_void_p``,
  ``c_char_p``, ``ctypes.POINTER(...)``, a ``byref`` slot);
- ``i64``  — 64-bit integers (``int64_t``/``uint64_t``/``size_t`` and
  ``c_int64``/``c_uint64``/``c_size_t``/``c_longlong``...);
- ``i32`` / ``i16`` / ``i8`` — the narrower integer widths;
- ``void`` — no return value (``restype = None``).

Signedness is deliberately NOT part of the class: the kernel ABI passes
both widths in the same registers and every current mismatch of
consequence is a width or arity drift.  The comparison runs in three
directions: every Python-declared function must exist in the C source
(missing symbol), with the same arity and per-slot width classes, and
— when the built ``.so`` is present — must resolve among its exported
dynamic symbols (``nm -D``, falling back to a ``ctypes`` load).

Parsing the C++ is a line-oriented scan, not a compiler: only
``extern "C"`` blocks are considered, comments are stripped, and a
definition is ``<ret> pn_<name>(<params>) {``.  That is exactly the
shape the in-tree kernels use; anything fancier (macros, typedef'd
params) would need this module taught about it — which is the point:
the gate fails closed on a signature it cannot classify.
"""

from __future__ import annotations

import os
import re
import ast
import subprocess

# ctypes expression (last attribute segment) -> width class.
_CTYPES_WIDTH = {
    "c_char_p": "ptr",
    "c_wchar_p": "ptr",
    "c_void_p": "ptr",
    "c_int64": "i64",
    "c_uint64": "i64",
    "c_longlong": "i64",
    "c_ulonglong": "i64",
    "c_size_t": "i64",
    "c_ssize_t": "i64",
    "c_int32": "i32",
    "c_uint32": "i32",
    "c_int": "i32",
    "c_uint": "i32",
    "c_int16": "i16",
    "c_uint16": "i16",
    "c_short": "i16",
    "c_ushort": "i16",
    "c_int8": "i8",
    "c_uint8": "i8",
    "c_byte": "i8",
    "c_ubyte": "i8",
    "c_char": "i8",
    "c_bool": "i8",
    "c_double": "f64",
    "c_float": "f32",
}

# C base-type token sequences -> width class (pointer handled first).
_C_WIDTH = {
    "int64_t": "i64",
    "uint64_t": "i64",
    "size_t": "i64",
    "ssize_t": "i64",
    "int32_t": "i32",
    "uint32_t": "i32",
    "int": "i32",
    "unsigned": "i32",
    "int16_t": "i16",
    "uint16_t": "i16",
    "short": "i16",
    "int8_t": "i8",
    "uint8_t": "i8",
    "char": "i8",
    "bool": "i8",
    "double": "f64",
    "float": "f32",
    "void": "void",
}

_LONG_TOKENS = {"long"}  # LP64: long / long long are both 64-bit here

_COMMENT_RE = re.compile(r"//[^\n]*|/\*.*?\*/", re.DOTALL)
_EXTERN_RE = re.compile(r'extern\s+"C"\s*\{')
_DEF_RE = re.compile(
    r"(?P<ret>[A-Za-z_][\w]*(?:\s+[A-Za-z_][\w]*)*\s*\**)\s*"
    r"\b(?P<name>pn_\w+)\s*\((?P<params>[^)]*)\)\s*\{",
    re.DOTALL,
)


class AbiIssue:
    """One conformance failure, anchored at a native.py line."""

    __slots__ = ("name", "line", "message")

    def __init__(self, name: str, line: int, message: str):
        self.name = name
        self.line = line
        self.message = message


def _c_slot_width(decl: str) -> str | None:
    """Width class of one C parameter (or return) declaration."""
    decl = decl.strip()
    if not decl or decl == "void":
        return "void" if decl == "void" else None
    if "*" in decl or "[" in decl:
        return "ptr"
    tokens = [t for t in re.split(r"\s+", decl) if t]
    # Drop qualifiers and the (optional) parameter name: the name is the
    # last token iff more than one type-ish token precedes it.
    tokens = [t for t in tokens if t not in ("const", "volatile", "struct")]
    if not tokens:
        return None
    if len(tokens) > 1 and tokens[-1] not in _C_WIDTH and tokens[-1] not in _LONG_TOKENS:
        tokens = tokens[:-1]  # trailing parameter name
    if any(t in _LONG_TOKENS for t in tokens):
        return "i64"
    for t in tokens:
        if t in _C_WIDTH:
            return _C_WIDTH[t]
    return None


def _extern_c_spans(text: str) -> list[tuple[int, int]]:
    """Character spans of every ``extern "C" { ... }`` block (brace
    matched)."""
    spans = []
    for m in _EXTERN_RE.finditer(text):
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        spans.append((m.end(), i))
    return spans


def parse_native_source(path: str) -> dict[str, tuple[str, list[str]]]:
    """``{name: (ret_width, [param_widths])}`` for every ``pn_*``
    function DEFINED inside an ``extern "C"`` block of the C++ source.
    Unclassifiable slots become ``"?"`` (compared unequal to anything,
    so the gate fails closed)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    text = _COMMENT_RE.sub(" ", text)
    out: dict[str, tuple[str, list[str]]] = {}
    for start, end in _extern_c_spans(text):
        for m in _DEF_RE.finditer(text, start, end):
            name = m.group("name")
            ret = _c_slot_width(m.group("ret")) or "?"
            params_src = m.group("params").strip()
            params: list[str] = []
            if params_src and params_src != "void":
                for p in params_src.split(","):
                    params.append(_c_slot_width(p) or "?")
            out[name] = (ret, params)
    return out


def _ctypes_width(node: ast.expr, aliases: dict[str, str]) -> str:
    """Width class of one ctypes argtypes element / restype expression."""
    if isinstance(node, ast.Constant) and node.value is None:
        return "void"
    if isinstance(node, ast.Call):
        # ctypes.POINTER(...) and friends
        fn = node.func
        last = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else ""
        )
        if last in ("POINTER", "CFUNCTYPE", "byref", "pointer"):
            return "ptr"
        return "?"
    if isinstance(node, ast.Attribute):
        return _CTYPES_WIDTH.get(node.attr, "?")
    if isinstance(node, ast.Name):
        if node.id in aliases:
            return aliases[node.id]
        return _CTYPES_WIDTH.get(node.id, "?")
    return "?"


def parse_ctypes_decls(path: str) -> dict[str, tuple[str, list[str], int]]:
    """``{name: (ret_width, [param_widths], line)}`` from the
    ``lib.pn_X.argtypes = [...]`` / ``.restype = ...`` assignments in
    native.py.  Local pointer aliases (``u8p = ctypes.POINTER(...)``)
    are resolved; the line anchors findings."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    aliases: dict[str, str] = {}
    args: dict[str, tuple[list[str], int]] = {}
    rets: dict[str, tuple[str, int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        # alias: <name> = ctypes.POINTER(...)
        if isinstance(tgt, ast.Name):
            w = _ctypes_width(node.value, aliases)
            if w != "?":
                aliases[tgt.id] = w
            continue
        # lib.pn_X.argtypes / lib.pn_X.restype
        if not (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Attribute)
            and tgt.value.attr.startswith("pn_")
        ):
            continue
        fn_name = tgt.value.attr
        if tgt.attr == "argtypes":
            if isinstance(node.value, (ast.List, ast.Tuple)):
                widths = [_ctypes_width(e, aliases) for e in node.value.elts]
            else:
                widths = ["?"]
            args[fn_name] = (widths, node.lineno)
        elif tgt.attr == "restype":
            rets[fn_name] = (_ctypes_width(node.value, aliases), node.lineno)
    out: dict[str, tuple[str, list[str], int]] = {}
    for name in sorted(set(args) | set(rets)):
        widths, aline = args.get(name, ([], 0))
        ret, rline = rets.get(name, ("void", 0))
        out[name] = (ret, widths, aline or rline or 1)
    return out


def so_symbols(path: str) -> set[str] | None:
    """Exported dynamic symbols of the built library, or None when the
    file is missing / unreadable (the export leg is then skipped —
    source-vs-declaration checking still runs)."""
    if not os.path.exists(path):
        return None
    try:
        res = subprocess.run(
            ["nm", "-D", "--defined-only", path],
            capture_output=True, text=True, timeout=30,
        )
        if res.returncode == 0 and res.stdout:
            syms = set()
            for ln in res.stdout.splitlines():
                parts = ln.split()
                if parts:
                    syms.add(parts[-1])
            return syms
    except (OSError, subprocess.SubprocessError):
        pass
    try:  # no nm: resolve each name through a live load instead
        import ctypes

        lib = ctypes.CDLL(path)
    except OSError:
        return None

    class _Probe(set):
        def __contains__(self, name) -> bool:  # pragma: no cover - fallback
            return hasattr(lib, name)

    return _Probe()


def check_abi(cpp_path: str, native_py_path: str,
              so_path: str | None = None) -> list[AbiIssue]:
    """Compare the three views of the native boundary; returns issues
    anchored at native.py lines (empty = conformant)."""
    issues: list[AbiIssue] = []
    c_defs = parse_native_source(cpp_path)
    decls = parse_ctypes_decls(native_py_path)
    exported = so_symbols(so_path) if so_path else None
    for name, (ret, widths, line) in sorted(decls.items()):
        c = c_defs.get(name)
        if c is None:
            issues.append(AbiIssue(
                name, line,
                f"`{name}` declared in native.py but not defined in any "
                f'extern "C" block of {os.path.basename(cpp_path)} — '
                "missing symbol (calling it jumps nowhere)",
            ))
            continue
        c_ret, c_params = c
        if len(widths) != len(c_params):
            issues.append(AbiIssue(
                name, line,
                f"`{name}` arity mismatch: native.py declares "
                f"{len(widths)} argtypes, the C definition takes "
                f"{len(c_params)} parameters — every later argument "
                "marshals into the wrong slot",
            ))
        else:
            for i, (pw, cw) in enumerate(zip(widths, c_params)):
                if pw != cw:
                    issues.append(AbiIssue(
                        name, line,
                        f"`{name}` argument {i} width mismatch: native.py "
                        f"declares {pw}, the C definition takes {cw}",
                    ))
        if ret != c_ret:
            issues.append(AbiIssue(
                name, line,
                f"`{name}` return width mismatch: native.py declares "
                f"{ret}, the C definition returns {c_ret}",
            ))
        if exported is not None and name not in exported:
            issues.append(AbiIssue(
                name, line,
                f"`{name}` is not among the .so's exported dynamic "
                "symbols — stale build or dropped export",
            ))
    return issues
