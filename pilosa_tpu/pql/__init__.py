"""PQL: the Pilosa Query Language.

Reference analog: pql/ (scanner.go, parser.go, ast.go, token.go).  Queries
are whitespace-separated call trees like::

    Count(Intersect(Bitmap(rowID=10, frame="stargazer"),
                    Bitmap(rowID=5, frame="language")))
    SetBit(rowID=1, frame="f", columnID=100)
    TopN(frame="f", n=20, field="category", filters=[1, 2])
    Range(rowID=1, frame="f", start="2017-01-01T00:00", end="2017-02-01T00:00")
"""

from pilosa_tpu.pql.ast import Call, Query, TIME_FORMAT  # noqa: F401
from pilosa_tpu.pql.parser import ParseError, parse, parse_cached  # noqa: F401
