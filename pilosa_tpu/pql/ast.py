"""PQL AST: Query and Call nodes plus typed arg helpers.

Reference analog: pql/ast.go — Query{Calls}, Call{Name, Args, Children}
(ast.go:26-57), UintArg/UintSliceArg accessors (ast.go:59-99),
WriteCallN mutation counting (ast.go:31-41), SupportsInverse/IsInverse
(ast.go:185-207), and deterministic String() rendering (ast.go:150-183).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# Timestamp layout for SetBit/Range args (pql/parser.go:25).
TIME_FORMAT = "%Y-%m-%dT%H:%M"

WRITE_CALL_NAMES = frozenset({"SetBit", "ClearBit", "SetRowAttrs", "SetColumnAttrs"})


@dataclass
class Call:
    name: str
    args: dict[str, Any] = field(default_factory=dict)
    children: list["Call"] = field(default_factory=list)

    # -- typed arg access (ast.go:59-99) --------------------------------

    def uint_arg(self, key: str) -> tuple[int, bool]:
        """(value, found); raises TypeError on a non-integer value."""
        if key not in self.args:
            return 0, False
        v = self.args[key]
        if isinstance(v, bool) or not isinstance(v, int):
            raise TypeError(f"could not convert {v!r} to uint64 in Call.uint_arg")
        return v, True

    def uint_slice_arg(self, key: str) -> tuple[list[int], bool]:
        if key not in self.args:
            return [], False
        v = self.args[key]
        if not isinstance(v, list) or any(isinstance(x, bool) or not isinstance(x, int) for x in v):
            raise TypeError(f"unexpected value in Call.uint_slice_arg: {v!r}")
        return list(v), True

    def string_arg(self, key: str, default: str = "") -> str:
        v = self.args.get(key, default)
        return v if isinstance(v, str) else default

    # -- inverse-view support (ast.go:185-207) --------------------------

    def supports_inverse(self) -> bool:
        return self.name == "Bitmap"

    def is_inverse(self, row_label: str, column_label: str) -> bool:
        """True when only the column arg is present on an invertible call."""
        if not self.supports_inverse():
            return False
        try:
            _, row_ok = self.uint_arg(row_label)
            _, col_ok = self.uint_arg(column_label)
        except TypeError:
            return False
        return (not row_ok) and col_ok

    # -- misc ------------------------------------------------------------

    def clone(self) -> "Call":
        return Call(
            name=self.name,
            args=dict(self.args),
            children=[c.clone() for c in self.children],
        )

    def __str__(self) -> str:
        parts = [str(c) for c in self.children]
        for key in sorted(self.args):
            v = self.args[key]
            if isinstance(v, str):
                parts.append(f'{key}="{v}"')
            elif isinstance(v, bool):
                parts.append(f"{key}={'true' if v else 'false'}")
            elif v is None:
                parts.append(f"{key}=null")
            elif isinstance(v, list):
                inner = ",".join(f'"{x}"' if isinstance(x, str) else str(x).lower() if isinstance(x, bool) else str(x) for x in v)
                parts.append(f"{key}=[{inner}]")
            else:
                parts.append(f"{key}={v}")
        return f"{self.name}({', '.join(parts)})"


@dataclass
class Query:
    calls: list[Call] = field(default_factory=list)

    def write_call_n(self) -> int:
        """Number of mutating calls (ast.go:31-41)."""
        return sum(1 for c in self.calls if c.name in WRITE_CALL_NAMES)

    def __str__(self) -> str:
        return "\n".join(str(c) for c in self.calls)
