"""PQL tokenizer + recursive-descent parser.

Reference analog: pql/scanner.go + pql/parser.go.  Token inventory matches
pql/token.go:22-46 (IDENT STRING INTEGER FLOAT EQ COMMA LPAREN RPAREN
LBRACK RBRACK); the grammar matches parser.go:66-260:

    query    := call*
    call     := IDENT '(' children? args? ')'
    children := call (',' call)*          (children precede args)
    args     := IDENT '=' value (',' IDENT '=' value)*
    value    := IDENT | STRING | INTEGER | FLOAT | '[' list ']'

``true``/``false``/``null`` idents become Python True/False/None; other
bare idents become strings (parser.go:172-183).  Identifiers may contain
letters, digits, ``_ - .`` after a leading letter (scanner.go:274-280);
numbers are integers or single-dot floats with optional leading minus
(scanner.go:155-180).

This implementation is a regex tokenizer + index-cursor parser (the
Python-native shape) rather than a rune scanner with unread stacks.
"""

from __future__ import annotations

import re
import threading

from pilosa_tpu.analysis import lockcheck
from typing import Any, NamedTuple

from pilosa_tpu.pql.ast import Call, Query


class ParseError(Exception):
    def __init__(self, message: str, line: int = 0, char: int = 0):
        super().__init__(f"{message} (line {line}, char {char})")
        self.message = message
        self.line = line
        self.char = char


_TOKEN_RE = re.compile(
    r"""
    (?P<WS>\s+)
  | (?P<IDENT>[A-Za-z][A-Za-z0-9_.-]*)
  | (?P<FLOAT>-?\d+\.\d*|-?\.\d+)
  | (?P<INTEGER>-?\d+)
  | (?P<STRING>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<EQ>=)
  | (?P<COMMA>,)
  | (?P<LPAREN>\()
  | (?P<RPAREN>\))
  | (?P<LBRACK>\[)
  | (?P<RBRACK>\])
  | (?P<ILLEGAL>.)
    """,
    re.VERBOSE | re.DOTALL,
)


class Token(NamedTuple):
    kind: str
    lit: str
    pos: int  # byte offset into the source; line/char derived on error


_UNESCAPE_RE = re.compile(r"\\(.)")


def _line_char(src: str, pos: int) -> tuple[int, int]:
    """Derive (line, char) from a source offset.  Position bookkeeping is
    deferred to error paths so the tokenize hot loop (thousands of tokens
    per batched query request) does no per-token arithmetic."""
    line = src.count("\n", 0, pos) + 1
    char = pos - (src.rfind("\n", 0, pos) + 1)
    return line, char


def tokenize(src: str) -> list[Token]:
    tokens: list[Token] = []
    append = tokens.append
    for m in _TOKEN_RE.finditer(src):
        kind = m.lastgroup
        if kind == "WS":
            continue
        lit = m.group()
        if kind == "ILLEGAL":
            raise ParseError(f"illegal character {lit!r}", *_line_char(src, m.start()))
        if kind == "STRING":
            lit = _UNESCAPE_RE.sub(r"\1", lit[1:-1])
        append(Token(kind, lit, m.start()))
    append(Token("EOF", "", len(src)))
    return tokens


class _Parser:
    def __init__(self, tokens: list[Token], src: str = ""):
        self.tokens = tokens
        self.src = src
        self.i = 0

    def fail(self, message: str, t: Token):
        raise ParseError(message, *_line_char(self.src, t.pos))

    def peek(self) -> Token:
        return self.tokens[self.i]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "EOF":
            # analysis-ok: check-then-act: _Parser is a per-parse stack object; it never crosses threads
            self.i += 1
        return t

    def expect(self, kind: str) -> Token:
        t = self.next()
        if t.kind != kind:
            self.fail(f"expected {kind}, found {t.lit!r}", t)
        return t

    def parse_query(self) -> Query:
        calls = []
        while self.peek().kind != "EOF":
            calls.append(self.parse_call())
        return Query(calls=calls)

    def parse_call(self) -> Call:
        name_tok = self.next()
        if name_tok.kind != "IDENT":
            self.fail(f"expected identifier, found: {name_tok.lit!r}", name_tok)
        self.expect("LPAREN")
        children = self.parse_children()
        args: dict[str, Any] = {}
        if self.peek().kind != "RPAREN":
            if children and self.peek().kind == "COMMA":
                self.next()
            args = self.parse_args()
        self.expect("RPAREN")
        return Call(name=name_tok.lit, args=args, children=children)

    def parse_children(self) -> list[Call]:
        children: list[Call] = []
        while (
            self.peek().kind == "IDENT"
            and self.i + 1 < len(self.tokens)
            and self.tokens[self.i + 1].kind == "LPAREN"
        ):
            children.append(self.parse_call())
            if self.peek().kind == "COMMA":
                # Only consume the comma if another child follows; otherwise
                # leave it for the args transition in parse_call.
                if (
                    self.i + 1 < len(self.tokens)
                    and self.tokens[self.i + 1].kind == "IDENT"
                    and self.i + 2 < len(self.tokens)
                    and self.tokens[self.i + 2].kind == "LPAREN"
                ):
                    self.next()
                else:
                    break
            else:
                break
        return children

    def parse_args(self) -> dict[str, Any]:
        args: dict[str, Any] = {}
        while True:
            if self.peek().kind == "RPAREN":
                return args
            key_tok = self.expect("IDENT")
            eq = self.next()
            if eq.kind != "EQ":
                self.fail(f"expected equals sign, found {eq.lit!r}", eq)
            value = self.parse_value()
            if key_tok.lit in args:
                self.fail(f"argument key already used: {key_tok.lit}", key_tok)
            args[key_tok.lit] = value
            t = self.peek()
            if t.kind == "RPAREN":
                return args
            if t.kind != "COMMA":
                self.fail(f"expected comma or right paren, found {t.lit!r}", t)
            self.next()

    def parse_value(self, in_list: bool = False) -> Any:
        t = self.next()
        if t.kind == "IDENT":
            if t.lit == "true":
                return True
            if t.lit == "false":
                return False
            if t.lit == "null" and not in_list:
                return None
            return t.lit
        if t.kind == "STRING":
            return t.lit
        if t.kind == "INTEGER":
            return int(t.lit)
        if t.kind == "FLOAT":
            return float(t.lit)
        if t.kind == "LBRACK" and not in_list:
            values = []
            while True:
                values.append(self.parse_value(in_list=True))
                sep = self.next()
                if sep.kind == "RBRACK":
                    return values
                if sep.kind != "COMMA":
                    self.fail(f"expected comma, found {sep.lit!r}", sep)
        self.fail(f"invalid argument value: {t.lit!r}", t)


_NATIVE_VALUES = {3: True, 4: False, 5: None}  # PN_V_TRUE/FALSE/NULL


def _parse_native(src: str):
    """Native C++ fast path (native/pilosa_native.cpp pn_pql_parse): the
    flat preorder call tree is rebuilt into Call objects here.  Returns
    None whenever the source needs the slow path — unsupported constructs
    OR any syntax error, so error messages always come from the Python
    parser and are byte-identical with or without the .so."""
    from pilosa_tpu import native

    try:
        raw = src.encode("utf-8")
    except UnicodeEncodeError:
        return None
    flat = native.pql_parse_flat(raw)
    if flat is None:
        return None
    (n, cname_s, cname_e, cnchild, cnargs, cargs_off,
     n_args, ak_s, ak_e, atype, aint, av_s, av_e) = flat
    # Slice to the used prefixes before tolist: the arrays are allocated at
    # source-length capacity, far larger than the parsed counts.
    cname_s = cname_s[:n].tolist()
    cname_e = cname_e[:n].tolist()
    cnchild = cnchild[:n].tolist()
    cnargs = cnargs[:n].tolist()
    cargs_off = cargs_off[:n].tolist()
    ak_s, ak_e = ak_s[:n_args].tolist(), ak_e[:n_args].tolist()
    atype, aint = atype[:n_args].tolist(), aint[:n_args].tolist()
    av_s, av_e = av_s[:n_args].tolist(), av_e[:n_args].tolist()

    def build(i: int) -> tuple[Call, int]:
        children = []
        j = i + 1
        for _ in range(cnchild[i]):
            child, j = build(j)
            children.append(child)
        args: dict[str, Any] = {}
        off = cargs_off[i]
        for a in range(off, off + cnargs[i]):
            t = atype[a]
            if t == 0:
                v: Any = aint[a]
            elif t in (1, 2):
                v = raw[av_s[a]:av_e[a]].decode("utf-8")
            else:
                v = _NATIVE_VALUES[t]
            args[raw[ak_s[a]:ak_e[a]].decode("utf-8")] = v
        return Call(name=raw[cname_s[i]:cname_e[i]].decode("utf-8"), args=args, children=children), j

    calls = []
    i = 0
    while i < n:
        call, i = build(i)
        calls.append(call)
    return Query(calls=calls)


# Singleton-write fast lane: `SetBit(k=1, frame="f", k2=2)`-shaped
# sources are the server's hottest parse (one per ingest request), and
# even the native parser's flat-array rebuild costs ~100 us of Python
# per call; this regex + split handles the flat no-nesting, no-list,
# int-or-plain-string argument shape in a few us.  Anything it can't
# express falls through to the normal parsers, so semantics and error
# messages are unchanged.
_SIMPLE_WRITE = re.compile(r"^\s*(SetBit|ClearBit)\s*\(([^()\[\]]*)\)\s*$")
_SIMPLE_STR = re.compile(r'^"[^"\\]*"$')


def _parse_simple_write(src: str):
    m = _SIMPLE_WRITE.match(src)
    if m is None:
        return None
    name, body = m.group(1), m.group(2)
    args: dict = {}
    for part in body.split(","):
        part = part.strip()
        if not part:
            return None
        k, eq, v = part.partition("=")
        if not eq:
            return None
        k, v = k.strip(), v.strip()
        if not k.isidentifier() or k in args:
            return None  # duplicate keys: the full parsers reject them
        if v.isascii() and v.isdigit():
            args[k] = int(v)
        elif _SIMPLE_STR.match(v):
            args[k] = v[1:-1]
        else:
            return None  # floats, bools, escapes, lists: slow path
    return Query(calls=[Call(name=name, args=args)])


def parse(src: str) -> Query:
    q = _parse_simple_write(src)
    if q is not None:
        return q
    q = _parse_native(src)
    if q is not None:
        return q
    return _Parser(tokenize(src), src).parse_query()


# Prepared-query cache: dashboards and importers re-send identical PQL
# request bodies; parsing is the dominant host cost of a large batched
# request, so identical sources hit a process-wide LRU.  Safe to share
# because the executor never mutates a parsed AST in place (TopN phase 2
# goes through Call.clone, executor analog of ast.go Clone).  Built
# through the named-global seam: bounded, every mutation under the
# "pql.parse_memo" lock, registered for the lockset detector and the
# /metrics inventory, and self-bypassing under an exploration run so
# cold-vs-warm cannot change a scenario's yield structure (this retired
# the PR 12 driver-thread warm-up in analysis/scenarios.py).  The key
# bound keeps megabyte import bodies out of the memo.
_PARSE_MEMO = lockcheck.named_global(
    "pql.parse_memo", max_entries=512, max_key_len=1 << 16
)


def parse_cached(src: str) -> Query:
    q = _PARSE_MEMO.get(src)
    if q is None:
        q = parse(src)  # outside the lock: a slow parse never serializes
        _PARSE_MEMO.put(src, q)
    return q
