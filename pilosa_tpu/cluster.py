"""Cluster topology and deterministic slice placement.

Reference analog: cluster.go.  Placement is kept bit-for-bit compatible
(SURVEY.md §7.5) so a mixed rollout agrees on ownership:

- slice → partition: FNV-1a 64 over (index name bytes + slice as 8-byte
  big-endian), mod PartitionN=256 (cluster.go:198-207),
- partition → nodes: jump consistent hash picks the primary, ReplicaN
  consecutive ring nodes replicate it (cluster.go:220-240, 266-277).

In the TPU build, this layer routes *across hosts*; within one host the
slice batch is mesh-sharded by GSPMD (pilosa_tpu.parallel) instead of
hash-routed.
"""

from __future__ import annotations

from dataclasses import dataclass

DEFAULT_PARTITION_N = 256
DEFAULT_REPLICA_N = 1

NODE_STATE_UP = "UP"
NODE_STATE_DOWN = "DOWN"


def fnv1a64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def jump_hash(key: int, n: int) -> int:
    """Jump consistent hash (Lamping & Veach) — key to bucket in [0, n)."""
    key &= 0xFFFFFFFFFFFFFFFF
    b, j = -1, 0
    while j < n:
        b = j
        key = (key * 2862933555777941757 + 1) & 0xFFFFFFFFFFFFFFFF
        j = int((b + 1) * ((1 << 31) / ((key >> 33) + 1)))
    return b


@dataclass(eq=False)  # identity hash: nodes are shared per-cluster instances
class Node:
    host: str
    internal_host: str = ""
    state: str = NODE_STATE_UP

    def to_json(self) -> dict:
        return {"host": self.host, "internalHost": self.internal_host, "state": self.state}


class Cluster:
    def __init__(
        self,
        nodes: list[Node] | None = None,
        replica_n: int = DEFAULT_REPLICA_N,
        partition_n: int = DEFAULT_PARTITION_N,
    ):
        self.nodes: list[Node] = nodes or []
        self.replica_n = replica_n
        self.partition_n = partition_n

    # -- membership ------------------------------------------------------

    def node_by_host(self, host: str):
        for n in self.nodes:
            if n.host == host:
                return n
        return None

    def node_set_hosts(self) -> list[str]:
        return [n.host for n in self.nodes]

    def up_nodes(self) -> list[Node]:
        return [n for n in self.nodes if n.state == NODE_STATE_UP]

    # -- placement (cluster.go:198-254) ----------------------------------

    def partition(self, index: str, slice_i: int) -> int:
        data = index.encode() + slice_i.to_bytes(8, "big")
        return fnv1a64(data) % self.partition_n

    def partition_nodes(self, partition_id: int) -> list[Node]:
        if not self.nodes:
            return []
        replica_n = min(self.replica_n, len(self.nodes)) or 1
        primary = jump_hash(partition_id, len(self.nodes))
        return [self.nodes[(primary + i) % len(self.nodes)] for i in range(replica_n)]

    def fragment_nodes(self, index: str, slice_i: int) -> list[Node]:
        return self.partition_nodes(self.partition(index, slice_i))

    def owns_fragment(self, host: str, index: str, slice_i: int) -> bool:
        return any(n.host == host for n in self.fragment_nodes(index, slice_i))

    def owns_slices(self, index: str, max_slice: int, host: str) -> list[int]:
        """Slices whose PRIMARY owner is host (cluster.go:243-254)."""
        out = []
        for i in range(max_slice + 1):
            p = self.partition(index, i)
            if self.nodes[jump_hash(p, len(self.nodes))].host == host:
                out.append(i)
        return out

    def slices_by_node(
        self,
        index: str,
        slices: list[int],
        exclude_down: bool = False,
        exclude_hosts: set | None = None,
    ) -> dict[Node, list[int]]:
        """Group slices by an owning node (executor.go:1095-1109).

        Each slice goes to its first eligible owner; with replicas, a down
        (or ``exclude_hosts``-listed, i.e. failed mid-query) primary falls
        through to the next replica — the placement half of the retry
        semantics of executor.go:1147-1159.
        """
        out: dict[Node, list[int]] = {}
        for s in slices:
            owners = self.fragment_nodes(index, s)
            chosen = None
            for node in owners:
                if exclude_down and node.state != NODE_STATE_UP:
                    continue
                if exclude_hosts and node.host in exclude_hosts:
                    continue
                chosen = node
                break
            if chosen is None:
                detail = "down or unreachable" if exclude_hosts else "down"
                raise RuntimeError(f"slice {s} unavailable: all owners {detail}")
            out.setdefault(chosen, []).append(s)
        return out

    def status_json(self) -> dict:
        return {
            "replicaN": self.replica_n,
            "partitionN": self.partition_n,
            "nodes": [n.to_json() for n in self.nodes],
        }
