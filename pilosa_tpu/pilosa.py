"""Package-level errors, constants, and name validation.

Reference analog: pilosa.go (sentinel errors pilosa.go:25-49, name/label
validation regexes pilosa.go:52-55 and 111-124).
"""

from __future__ import annotations

import re

# Slice width: number of columns per slice. Reference: fragment.go:47
# (SliceWidth = 1048576 = 2^20). Everything hangs off this constant.
SLICE_WIDTH = 1 << 20


class PilosaError(Exception):
    """Base class for all framework errors."""


class ErrIndexExists(PilosaError):
    pass


class ErrIndexNotFound(PilosaError):
    pass


class ErrFrameExists(PilosaError):
    pass


class ErrFrameNotFound(PilosaError):
    pass


class ErrFrameInverseDisabled(PilosaError):
    pass


class ErrFragmentNotFound(PilosaError):
    pass


class ErrFragmentLocked(PilosaError):
    """Another process holds the fragment's exclusive file lock
    (fragment.go:179-234 flock analog)."""


class ErrFragmentClosed(PilosaError):
    """Read/write against a closed fragment — close() swaps in an empty
    bitmap to release the mmap, so without this guard a late reader
    would silently see no data instead of an error."""


class ErrQueryRequired(PilosaError):
    pass


class ErrInvalidView(PilosaError):
    pass


class ErrName(PilosaError):
    pass


class ErrLabel(PilosaError):
    pass


class ErrHostRequired(PilosaError):
    pass


class ErrFrameRequired(PilosaError):
    pass


class ErrColumnRowLabelEqual(PilosaError):
    pass


class ErrInvalidCacheType(PilosaError):
    pass


class ErrInvalidTimeQuantum(PilosaError):
    pass


class ErrTooManyWrites(PilosaError):
    pass


# Reference: pilosa.go:52-55 — names are lowercase alphanumeric with
# dash/underscore, a leading letter, at most 65 chars total.
_NAME_RE = re.compile(r"[a-z][a-z0-9_-]{0,64}")
_LABEL_RE = re.compile(r"[A-Za-z][A-Za-z0-9_-]{0,64}")


def validate_name(name: str) -> None:
    if not isinstance(name, str) or _NAME_RE.fullmatch(name) is None:
        raise ErrName(f"invalid index or frame name: {name!r}")


def validate_label(label: str) -> None:
    if not isinstance(label, str) or _LABEL_RE.fullmatch(label) is None:
        raise ErrLabel(f"invalid row or column label: {label!r}")


# ---------------------------------------------------------------------------
# Shared batch-chunk sizing for the multi-view OR gather (fused Range).
# One source of truth for the three evaluators (numpy engine, mesh engine,
# dispatch's XLA fallback): a materialized [S, chunk, V, W] gather must
# stay under budget bytes.  Hosts chunk small (L3-cache friendly); device
# engines afford a larger HBM transient.
# ---------------------------------------------------------------------------

OR_MULTI_BUDGET_HOST = 32 << 20
OR_MULTI_BUDGET_DEVICE = 256 << 20


def or_multi_chunk_size(n_slices: int, n_views: int, n_words: int, budget: int) -> int:
    """Largest batch chunk whose gathered block fits ``budget`` bytes."""
    return max(1, budget // max(1, n_slices * n_views * n_words * 4))
