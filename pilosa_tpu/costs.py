"""Per-fingerprint cost ledger + device-dispatch cost attribution.

No reference analog — the reference's observability stops at aggregate
expvar counters.  This module is the feedback substrate ROADMAP item 4's
trace-driven adaptive planner consumes: per-(index, frame, query
fingerprint, strategy lane) observed costs and fetch bandwidth, in one
queryable place (``/debug/costs``).

Two halves:

- :class:`DispatchMeter` — device-side cost attribution at the engine
  dispatch seams (gram / gather / stream / native lanes).  Each metered
  dispatch emits a tagged histogram (``engine.dispatch_ms.<lane>``), a
  transfer-byte counter (``engine.dispatch_bytes.<lane>``, read as a
  delta of the engine's host->device upload ledger plus explicitly
  reported operand bytes), and — when the request is traced — a
  ``device`` child span tagged with the lane and bytes, so a trace
  finally shows device time, not just host time.  The disabled path
  (``meter is None`` at every call site) adds one branch per site, the
  same contract as tracing.
- :class:`CostLedger` — a bounded LRU ring keyed by (index, frame,
  fingerprint, lane) folding finished traces into EWMA cost/bandwidth
  estimates.  The tracer calls :meth:`CostLedger.fold` from
  ``finish_request`` for every recorded trace (sampled or slow), so the
  ledger rides the existing trace stream: no new per-request work on
  the unsampled fast path.

Enable/disable: the server and lockstep front end construct the meter
and ledger unless ``PILOSA_TPU_COSTS`` is falsy ("0"/"false"/"no"); the
bench overhead gate (bench.py costs_overhead_check) asserts the enabled
path costs <= 5% vs disabled, like the trace sample-rate bound.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Optional

from pilosa_tpu.analysis import lockcheck

# Ledger capacity default: one entry per distinct (index, frame,
# fingerprint, lane); dashboards repeat a small set of shapes, so a few
# hundred entries cover steady state.
DEFAULT_CAP = 512
# EWMA smoothing: ~the last ~8 observations dominate.
DEFAULT_ALPHA = 0.25


def enabled_from_env() -> bool:
    import os

    return os.environ.get("PILOSA_TPU_COSTS", "").lower() not in ("0", "false", "no")


class _Measure:
    """One metered dispatch (context manager): wall time from enter to
    exit, transfer bytes = the engine upload-ledger delta plus anything
    the caller adds explicitly via :meth:`add_bytes`."""

    __slots__ = ("meter", "lane", "span", "t0", "extra_bytes", "up0", "dev_span")

    def __init__(self, meter: "DispatchMeter", lane: str, span):
        self.meter = meter
        self.lane = lane
        self.span = span
        self.extra_bytes = 0
        self.dev_span = None

    def add_bytes(self, n: int) -> None:
        # analysis-ok: check-then-act: _Measure is a per-request stack object; it never crosses threads
        self.extra_bytes += int(n)

    def __enter__(self) -> "_Measure":
        if self.span is not None:
            self.dev_span = self.span.child("device")
            self.dev_span.tags["lane"] = self.lane
        self.up0 = getattr(self.meter.engine, "stat_upload_bytes", 0)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt_ms = (time.perf_counter() - self.t0) * 1e3
        moved = (
            getattr(self.meter.engine, "stat_upload_bytes", 0) - self.up0
        ) + self.extra_bytes
        self.meter._record(self.lane, dt_ms, moved, self.dev_span)
        return False


class DispatchMeter:
    """Per-dispatch device cost attribution (see module docstring).

    Thread-safe by construction: stats clients lock internally, span
    child creation is append-only under the GIL, and the engine upload
    ledger is a plain int read twice — a concurrent uploader can skew
    one dispatch's byte delta, which is acceptable for attribution."""

    __slots__ = ("stats", "engine")

    def __init__(self, stats=None, engine=None):
        from pilosa_tpu.stats import NOP_STATS

        self.stats = stats if stats is not None else NOP_STATS
        self.engine = engine

    def measure(self, lane: str, span=None) -> _Measure:
        return _Measure(self, lane, span)

    def _record(self, lane: str, dt_ms: float, moved: int, dev_span) -> None:
        self.stats.histogram(f"engine.dispatch_ms.{lane}", dt_ms)
        if moved > 0:
            self.stats.count(f"engine.dispatch_bytes.{lane}", int(moved))
        if dev_span is not None:
            dev_span.finish()
            if moved > 0:
                dev_span.tags["bytes"] = int(moved)

    def resident(self, hbm_bytes: int) -> None:
        """Gauge the engine's HBM-resident working set (the executor
        reports its matrix/serve-state cache totals after mutations)."""
        self.stats.gauge("engine.hbm_bytes", int(hbm_bytes))


@lockcheck.guarded_class
class CostLedger:
    """Bounded LRU of EWMA cost/bandwidth estimates keyed by
    (tenant, index, frame, fingerprint, lane) — the /debug/costs
    payload and the per-tenant ledger rows /debug/tenants bills from.

    The tenant dimension is real (not ``tenant or index`` conflated):
    two tenants sharing one index keep separate estimates.  Readers
    that don't know the tenant (the planner's peeks) resolve through a
    secondary (index, frame, fp, lane) -> full-key map that tracks the
    most recently observed tenant for each 4-tuple."""

    _guarded_by_ = {"_entries": "costs._mu", "_by4": "costs._mu"}

    def __init__(self, cap: int = DEFAULT_CAP, alpha: float = DEFAULT_ALPHA,
                 stats=None):
        from pilosa_tpu.stats import NOP_STATS

        self.cap = max(1, int(cap))
        self.alpha = min(1.0, max(0.01, float(alpha)))
        self.stats = stats if stats is not None else NOP_STATS
        self._mu = lockcheck.named_lock("costs._mu")
        self._entries: "OrderedDict[tuple, dict]" = OrderedDict()
        # (index, frame, fp, lane) -> full 5-tuple key, MRU tenant wins.
        self._by4: dict[tuple, tuple] = {}

    def observe(
        self,
        *,
        tenant: str = "",
        index: str = "",
        frame: str = "",
        fp: str = "",
        lane: str = "",
        ms: float,
        bytes_moved: int = 0,
        device_ms: float = 0.0,
        wall_ts: Optional[float] = None,
    ) -> None:
        """Fold one observation into the (tenant, index, frame, fp,
        lane) entry.  Bandwidth (MB/s) only updates when the observation
        actually moved bytes, so transfer-free warm hits don't decay
        it."""
        key = (tenant, index, frame, fp, lane)
        # analysis-ok: lockstep-determinism: display-only last_ts metadata; lockstep folds happen on rank 0 alone (workers carry no planner) and never feed a wire decision
        ts = wall_ts if wall_ts is not None else time.time()
        a = self.alpha
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = {
                    "n": 0,
                    "ewma_ms": float(ms),
                    "ewma_device_ms": float(device_ms),
                    "ewma_mbps": 0.0,
                    "last_ms": 0.0,
                    "last_ts": 0.0,
                }
                while len(self._entries) > self.cap:
                    old_key, _ = self._entries.popitem(last=False)
                    if self._by4.get(old_key[1:]) == old_key:
                        del self._by4[old_key[1:]]
                    self.stats.count("costs.evict")
            e["n"] += 1
            e["ewma_ms"] += a * (float(ms) - e["ewma_ms"])
            if device_ms > 0:
                e["ewma_device_ms"] += a * (float(device_ms) - e["ewma_device_ms"])
            if bytes_moved > 0 and ms > 0:
                mbps = bytes_moved / (ms / 1e3) / 1e6
                if e["ewma_mbps"] == 0.0:
                    e["ewma_mbps"] = mbps
                else:
                    e["ewma_mbps"] += a * (mbps - e["ewma_mbps"])
            e["last_ms"] = round(float(ms), 3)
            e["last_ts"] = round(ts, 3)
            self._entries.move_to_end(key)
            self._by4[key[1:]] = key
            n_entries = len(self._entries)
        self.stats.count("costs.fold")
        self.stats.gauge("costs.entries", n_entries)

    def fold(self, trace, dt_ms: float, body: bytes = b"") -> None:
        """Fold one finished trace (trace.Trace) into the ledger: the
        root's lane/tenant/frame tags key the entry; ``device`` child
        spans (the dispatch meter's) contribute device time and bytes.
        Called by Tracer.finish_request for every recorded trace."""
        from pilosa_tpu.trace import fingerprint

        root = trace.root
        tags = root.tags
        tenant = str(tags.get("tenant") or "")
        # Embedders that only tagged "tenant" (the pre-tenancy handler
        # wrote the index name there) keep their index keying.
        index = str(tags.get("index") or "") or tenant
        lane = str(tags.get("lane") or "general")
        frame = str(tags.get("frame") or "")
        fp = fingerprint(body)["fp"] if body else ""
        device_ms = 0.0
        bytes_moved = 0
        stack = [root]
        while stack:
            sp = stack.pop()
            children = (
                sp.get("children", []) if isinstance(sp, dict) else sp.children
            )
            for c in children:
                if isinstance(c, dict):
                    if c.get("name") == "device":
                        ctags = c.get("tags", {})
                        device_ms += float(c.get("ms") or 0.0)
                        bytes_moved += int(ctags.get("bytes") or 0)
                    else:
                        stack.append(c)
                else:
                    if c.name == "device":
                        device_ms += float(c.ms or 0.0)
                        bytes_moved += int(c.tags.get("bytes") or 0)
                    else:
                        stack.append(c)
        self.observe(
            tenant=tenant,
            index=index,
            frame=frame,
            fp=fp,
            lane=lane,
            ms=dt_ms,
            bytes_moved=bytes_moved,
            device_ms=device_ms,
            wall_ts=trace.wall_ts,
        )

    def peek(
        self, *, tenant: Optional[str] = None, index: str = "",
        frame: str = "", fp: str = "", lane: str = ""
    ) -> Optional[dict]:
        """One entry's current estimates (a copy), or None.  Pure read:
        the LRU order is NOT bumped — the planner consults on every
        request and must not pin its own keys hot.  ``tenant=None``
        (the planner's tenant-agnostic peeks) resolves through the
        MRU-tenant map for the 4-tuple."""
        with self._mu:
            if tenant is not None:
                e = self._entries.get((tenant, index, frame, fp, lane))
            else:
                full = self._by4.get((index, frame, fp, lane))
                e = self._entries.get(full) if full is not None else None
            return dict(e) if e is not None else None

    def entries(self, lane: Optional[str] = None) -> list[dict]:
        """Entry copies (optionally one lane's), unsorted and unrounded
        — the adaptive-budget derivations read these."""
        with self._mu:
            return [
                {"tenant": k[0], "index": k[1], "frame": k[2], "fp": k[3],
                 "lane": k[4], **v}
                for k, v in self._entries.items()
                if lane is None or k[4] == lane
            ]

    def by_tenant(self) -> dict:
        """Per-tenant ledger aggregates for /debug/tenants: entry count
        and total observed cost (n * ewma_ms, the billing proxy).
        Entries folded before the tenant dimension existed bill to
        their index (the pre-tenancy attribution)."""
        with self._mu:
            out: dict = {}
            for k, e in self._entries.items():
                t = k[0] or k[1] or ""
                row = out.setdefault(t, {"entries": 0, "cost_ms": 0.0})
                row["entries"] += 1
                row["cost_ms"] += e["n"] * e["ewma_ms"]
        for row in out.values():
            row["cost_ms"] = round(row["cost_ms"], 3)
        return out

    def state(self) -> dict:
        """Full restorable state (entries in LRU order).  With
        :meth:`restore` this makes the EWMA fold deterministic across a
        snapshot/restore cycle: folding the same observations into a
        restored ledger yields bit-identical estimates."""
        with self._mu:
            return {
                "cap": self.cap,
                "alpha": self.alpha,
                "entries": [[list(k), dict(v)] for k, v in self._entries.items()],
            }

    def restore(self, st: dict) -> None:
        self.cap = max(1, int(st.get("cap", self.cap)))
        self.alpha = min(1.0, max(0.01, float(st.get("alpha", self.alpha))))
        with self._mu:
            self._entries.clear()
            self._by4.clear()
            for k, v in st.get("entries", []):
                key = tuple(k)
                if len(key) == 4:
                    # Pre-tenancy snapshot: pad with an empty tenant.
                    key = ("",) + key
                self._entries[key] = dict(v)
                self._by4[key[1:]] = key

    def snapshot(self, limit: int = 0) -> dict:
        """The /debug/costs payload: entries sorted by EWMA cost
        descending (the planner's priority order)."""
        with self._mu:
            items = [
                {"tenant": k[0], "index": k[1], "frame": k[2], "fp": k[3],
                 "lane": k[4], **v}
                for k, v in self._entries.items()
            ]
        items.sort(key=lambda e: -e["ewma_ms"])
        if limit > 0:
            items = items[:limit]
        for e in items:
            e["ewma_ms"] = round(e["ewma_ms"], 3)
            e["ewma_device_ms"] = round(e["ewma_device_ms"], 3)
            e["ewma_mbps"] = round(e["ewma_mbps"], 3)
        return {"cap": self.cap, "alpha": self.alpha, "entries": items}

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)
