"""HTTP API handler: the reference's full route table on stdlib http.server.

Reference analog: handler.go (1429 LoC; route table handler.go:82-120).
Routes:

    GET    /                                        welcome (API) / WebUI (browser)
    GET    /assets/{file}                           WebUI assets
    GET    /index                                   list indexes
    GET    /index/{index}                           index info
    POST   /index/{index}                           create index
    DELETE /index/{index}                           delete index
    POST   /index/{index}/attr/diff                 column attr-diff (sync)
    POST   /index/{index}/frame/{frame}             create frame
    DELETE /index/{index}/frame/{frame}             delete frame
    POST   /index/{index}/query                     PQL query (JSON or protobuf)
    POST   /index/{index}/frame/{frame}/attr/diff   row attr-diff (sync)
    POST   /index/{index}/frame/{frame}/restore     restore frame from peers
    PATCH  /index/{index}/frame/{frame}/time-quantum
    GET    /index/{index}/frame/{frame}/views
    PATCH  /index/{index}/time-quantum
    GET    /debug/vars                              expvar-style stats
    GET    /debug/pprof/...                         thread/profile dump
    GET    /export                                  CSV export
    GET    /fragment/block/data                     block bit data (protobuf)
    GET    /fragment/blocks                         block checksums
    GET    /fragment/data                           raw fragment snapshot
    POST   /fragment/data                           replace fragment (restore)
    GET    /fragment/nodes                          owner nodes for a slice
    POST   /import                                  bulk import (protobuf)
    GET    /hosts                                   cluster hosts
    GET    /schema                                  full schema
    GET    /slices/max                              per-index max slice
    GET    /status                                  cluster status
    GET    /version

Content negotiation mirrors handler.go:816-898: requests/responses use
``application/x-protobuf`` when the Content-Type/Accept headers ask for
it, JSON otherwise.
"""

from __future__ import annotations

import io
import json
import os
import queue
import re
import socket
import threading
import time
import traceback
import zlib
from datetime import datetime
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from pilosa_tpu import pilosa as errors
from pilosa_tpu.analysis import lockcheck
from pilosa_tpu import pql, qcache as qcache_mod, qos, trace as trace_mod, wire
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.executor import ExecOptions, QueryBitmap
from pilosa_tpu.pilosa import SLICE_WIDTH, PilosaError

VERSION = "0.1.0-tpu"

PROTOBUF = "application/x-protobuf"

# Tenant attribution goes through the single tenancy.resolve seam
# (header > [tenancy] map > index name): trace tags, slow-query log
# lines, the cost ledger, and the admission doors can never disagree
# on a request's tenant.  See _resolve_tenant.
from pilosa_tpu import tenancy as tenancy_mod


class HTTPError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def result_to_json(result):
    if isinstance(result, QueryBitmap):
        return result.to_json()
    if isinstance(result, list) and (not result or isinstance(result[0], Pair)):
        return [p.to_json() for p in result]
    return result


class Handler:
    """Routes requests to the holder/executor; transport-agnostic core."""

    def __init__(self, holder, executor, cluster=None, host="", broadcaster=None, stats=None, client_factory=None,
                 admission=None, default_deadline_ms: float = 0.0, tracer=None,
                 group: str = "", applied_seq=None,
                 ingest_chunk_bytes: int = 4 << 20, costs=None,
                 planner=None,
                 bulk_batch_slices: int = 8,
                 bulk_materialize_budget_ms: float = 0.0,
                 tenancy=None):
        self.holder = holder
        self.executor = executor
        self.cluster = cluster
        self.host = host
        self.broadcaster = broadcaster  # schema-mutation broadcast hook
        self.stats = stats
        self._profiling = None  # active jax trace dir, if any
        self.client_factory = client_factory
        # Request-lifecycle QoS: the per-class admission gate (None =
        # unbounded, the pre-QoS behavior) and the server's default
        # deadline for requests that carry no X-Pilosa-Deadline-Ms.
        self.admission = admission
        self.default_deadline_ms = default_deadline_ms
        # Request-scoped span tracer (trace.Tracer); None = no tracing
        # at all (embedders) — the server always passes one so the
        # X-Pilosa-Trace force override works without a restart.
        self.tracer = tracer
        # Per-fingerprint cost ledger (costs.CostLedger), served at
        # /debug/costs; None = ledger disabled (endpoint answers empty).
        self.costs = costs
        # Cost-based planner (planner.Planner): this handler is the
        # CONSULTATION point — post_query fingerprints the body and
        # attaches the plan to ExecOptions; the executor only applies.
        # None = static strategy ladder everywhere (the default).
        self.planner = planner
        # Multi-tenant isolation (tenancy.TenancyState): the resolution
        # seam + fair-share/quota/pacer state.  None = isolation off —
        # attribution falls back to the index name and no door enforces.
        self.tenancy = tenancy
        # Replica serving-group identity ("name" or "name@epoch",
        # [replica] group): stamped on every response as X-Pilosa-Group
        # so the router can record which group answered and detect
        # epoch bumps across restarts.
        self.group = group
        # Last-applied router write sequence (replica durability): the
        # router tags every sequenced write with X-Pilosa-Write-Seq;
        # the handler notes it once the route answers deterministically
        # and reports it back (X-Pilosa-Applied-Seq + /replica/health)
        # so the router can stream exactly the missed WAL suffix to a
        # restarted group.  The Server passes a disk-backed AppliedSeq;
        # group-tagged embedders get an in-memory one.
        if applied_seq is None and group:
            from pilosa_tpu.replica.catchup import AppliedSeq

            applied_seq = AppliedSeq()
        self.applied_seq = applied_seq
        # Resync chunk staging (POST /fragment/import-roaring): one
        # in-progress transfer buffer per fragment path, keyed with the
        # whole payload's (total, crc) so a resumed transfer continues
        # and a different payload restarts cleanly.  Memory only — a
        # crashed group simply restarts the transfer.
        self._resync_mu = lockcheck.named_lock("server.handler._resync_mu")
        self._resync_staging: dict[tuple, dict] = {}
        # Streaming columnar bulk-ingest door (POST .../ingest): chunks
        # apply as they arrive through the batched set_bits path; the
        # stager holds offsets + running CRC only, never payloads.
        from pilosa_tpu import ingest as ingest_mod

        self._ingestor = ingest_mod.StreamIngestor(
            self._ingest_apply,
            complete=self._ingest_complete,
            stats=stats,
            max_chunk_bytes=ingest_chunk_bytes,
        )
        # Device-first bulk build door (POST .../bulk): same chunk wire
        # as the streamed door, but chunks run the engine's
        # sort/segment/scatter build and commit word planes as pending
        # fragment overlays — roaring stays lazy (pilosa_tpu/bulk).
        self.bulk_batch_slices = bulk_batch_slices
        self.bulk_materialize_budget_ms = bulk_materialize_budget_ms
        self._bulk_ingestor = ingest_mod.StreamIngestor(
            self._bulk_apply,
            complete=self._bulk_complete,
            stats=stats,
            max_chunk_bytes=ingest_chunk_bytes,
        )
        self.version = VERSION
        self._routes = self._build_routes()

    # -- routing -------------------------------------------------------

    def _build_routes(self):
        return [
            ("GET", re.compile(r"^/$"), self.get_root),
            ("GET", re.compile(r"^/assets/(?P<file>[^/]+)$"), self.get_webui_asset),
            ("GET", re.compile(r"^/index$"), self.get_indexes),
            ("GET", re.compile(r"^/index/(?P<index>[^/]+)$"), self.get_index),
            ("POST", re.compile(r"^/index/(?P<index>[^/]+)$"), self.post_index),
            ("DELETE", re.compile(r"^/index/(?P<index>[^/]+)$"), self.delete_index),
            ("POST", re.compile(r"^/index/(?P<index>[^/]+)/attr/diff$"), self.post_index_attr_diff),
            ("POST", re.compile(r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)$"), self.post_frame),
            ("DELETE", re.compile(r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)$"), self.delete_frame),
            ("POST", re.compile(r"^/index/(?P<index>[^/]+)/query$"), self.post_query),
            ("POST", re.compile(r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/ingest$"), self.post_frame_ingest),
            ("POST", re.compile(r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/bulk$"), self.post_frame_bulk),
            ("POST", re.compile(r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/attr/diff$"), self.post_frame_attr_diff),
            ("POST", re.compile(r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/restore$"), self.post_frame_restore),
            ("PATCH", re.compile(r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/time-quantum$"), self.patch_frame_time_quantum),
            ("GET", re.compile(r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/views$"), self.get_frame_views),
            ("PATCH", re.compile(r"^/index/(?P<index>[^/]+)/time-quantum$"), self.patch_index_time_quantum),
            ("GET", re.compile(r"^/replica/health$"), self.get_replica_health),
            ("GET", re.compile(r"^/replica/digest$"), self.get_replica_digest),
            ("POST", re.compile(r"^/replica/seed-seq$"), self.post_replica_seed_seq),
            ("POST", re.compile(r"^/fragment/import-roaring$"), self.post_fragment_import_roaring),
            ("GET", re.compile(r"^/debug/vars$"), self.get_expvar),
            ("GET", re.compile(r"^/debug/traces$"), self.get_debug_traces),
            ("GET", re.compile(r"^/debug/costs$"), self.get_debug_costs),
            ("GET", re.compile(r"^/debug/planner$"), self.get_debug_planner),
            ("GET", re.compile(r"^/debug/tenants$"), self.get_debug_tenants),
            ("GET", re.compile(r"^/metrics$"), self.get_metrics),
            ("GET", re.compile(r"^/debug/pprof(?:/(?P<path>.*))?$"), self.get_pprof),
            ("POST", re.compile(r"^/debug/profile/start$"), self.post_profile_start),
            ("POST", re.compile(r"^/debug/profile/stop$"), self.post_profile_stop),
            ("GET", re.compile(r"^/export$"), self.get_export),
            ("GET", re.compile(r"^/fragment/block/data$"), self.get_fragment_block_data),
            ("POST", re.compile(r"^/fragment/block/diff$"), self.post_fragment_block_diff),
            ("GET", re.compile(r"^/fragment/blocks$"), self.get_fragment_blocks),
            ("GET", re.compile(r"^/fragment/data$"), self.get_fragment_data),
            ("POST", re.compile(r"^/fragment/data$"), self.post_fragment_data),
            ("GET", re.compile(r"^/fragment/nodes$"), self.get_fragment_nodes),
            ("POST", re.compile(r"^/import$"), self.post_import),
            ("GET", re.compile(r"^/hosts$"), self.get_hosts),
            ("GET", re.compile(r"^/schema$"), self.get_schema),
            ("GET", re.compile(r"^/slices/max$"), self.get_slices_max),
            ("GET", re.compile(r"^/status$"), self.get_status),
            ("GET", re.compile(r"^/version$"), self.get_version),
        ]

    def dispatch(self, method: str, path: str, params: dict, body: bytes, headers: dict):
        """Returns (status, content_type, payload bytes[, extra headers]).

        The TRACE door wraps the QoS door: the head-sampling decision is
        made once here (``X-Pilosa-Trace`` forces it — the client
        override and the cross-node hop), the root span rides down into
        the route (post_query threads it through ExecOptions into the
        executor), and at completion the tracer records the ring entry,
        emits the slow-query log line for any request past ``slow-ms``
        (sampled or not), and — for propagated traces — returns the
        serialized span tree in the ``X-Pilosa-Trace-Spans`` response
        header so the coordinator grafts the peer's sub-spans.  With no
        tracer (embedders) this wrapper is a single branch.
        """
        tracer = self.tracer
        if tracer is None:
            out = self._dispatch_qos(method, path, params, body, headers, None)
            self._note_applied(headers, out)
            return self._with_group(out)
        trace = tracer.begin(headers, name=f"{method} {path}")
        if trace is not None and headers.get("x-pilosa-replay"):
            # Catch-up replays are router-originated re-deliveries, not
            # client traffic: tag the root so /debug/traces (and the
            # slow-query log) can split replay load from live load.
            trace.root.tags["replay"] = True
        t0 = time.perf_counter()
        out = self._dispatch_qos(
            method, path, params, body, headers, trace.root if trace else None
        )
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._note_applied(headers, out)
        # An UNSAMPLED request crossing slow-ms synthesizes a root-only
        # trace inside finish_request; hand it the QoS class + tenant
        # tags it never got from _dispatch_qos (computed only on the
        # slow path — the fast path stays one comparison).
        tags = None
        if trace is None and tracer.slow_ms > 0.0 and dt_ms >= tracer.slow_ms:
            tags = {"qos_class": qos.classify_request(method, path, body)}
            tenant, index = self._resolve_tenant(path, headers)
            if tenant:
                tags["tenant"] = tenant
            if index:
                tags["index"] = index
        extra = tracer.finish_request(
            trace, name=f"{method} {path}", dt_ms=dt_ms, body=body,
            status=out[0], tags=tags,
        )
        if extra:
            merged = dict(out[3]) if len(out) > 3 else {}
            merged.update(extra)
            out = (out[0], out[1], out[2], merged)
        return self._with_group(out)

    def _note_applied(self, headers: dict, out) -> None:
        """Advance the applied-sequence mark when this request carried
        the router's write sequence and answered deterministically.
        The whole response tuple rides in so the shared not-applied
        predicate sees a shed's Retry-After even on a <500 status."""
        if self.applied_seq is None:
            return
        from pilosa_tpu.replica.catchup import note_applied_from_headers

        extra = out[3] if len(out) > 3 else {}
        note_applied_from_headers(self.applied_seq, headers, out[0],
                                  retry_after=extra.get("Retry-After"))

    def _with_group(self, out):
        """Stamp the serving group's identity (and its applied-sequence
        high-water mark — the router's passive lag tracking) on every
        response — per-read attribution plus the epoch-bump signal."""
        if not self.group:
            return out
        from pilosa_tpu.replica import APPLIED_SEQ_HEADER, GROUP_HEADER

        merged = dict(out[3]) if len(out) > 3 else {}
        merged.setdefault(GROUP_HEADER, self.group)
        if self.applied_seq is not None:
            merged.setdefault(APPLIED_SEQ_HEADER, str(self.applied_seq.value))
        return (out[0], out[1], out[2], merged)

    def _dispatch_qos(self, method: str, path: str, params: dict, body: bytes,
                      headers: dict, span=None):
        """The QoS door wraps every route: the request's deadline is built
        once (header > configured default), the request is classified
        (read / write / admin) and admitted through the per-class
        bounded gate — a full door answers 429 + Retry-After
        immediately, an expired deadline answers 504 BEFORE the route
        executes, and per-class latency lands in the stats histograms
        that /debug/vars serves.
        """
        deadline = qos.deadline_from_headers(headers, self.default_deadline_ms)
        cls = qos.classify_request(method, path, body)
        tenant, index = self._resolve_tenant(path, headers)
        if span is not None:
            # QoS class + tenant tag (the shared tenancy.resolve seam):
            # every trace (and slow-query log line, which surfaces root
            # tags flat) attributes to its tenant.
            span.tags["qos_class"] = cls
            if tenant:
                span.tags["tenant"] = tenant
            if index:
                span.tags["index"] = index
        # Fair-share enforcement engages only with tenancy ON; off, the
        # door sees tenant=None and behaves byte-identically to today.
        door_tenant = tenant if self.tenancy is not None else None
        t0 = time.perf_counter()
        try:
            if self.admission is not None:
                asp = span.child("qos.admit") if span is not None else None
                with self.admission.admit(cls, deadline, tenant=door_tenant):
                    if asp is not None:
                        asp.finish()
                    if deadline is not None:
                        deadline.check("admission")
                    return self._dispatch_route(method, path, params, body, headers,
                                                deadline, span)
            if deadline is not None and deadline.expired():
                raise qos.DeadlineExceeded("admission")
            return self._dispatch_route(method, path, params, body, headers,
                                        deadline, span)
        except qos.ShedError as e:
            if span is not None:
                span.tags["qos"] = "shed"
            return (
                e.status,
                "application/json",
                json.dumps({"error": str(e)}).encode(),
                {"Retry-After": f"{e.retry_after:.3f}"},
            )
        except qos.DeadlineExceeded as e:
            if span is not None:
                span.tags["qos"] = "expired"
            if self.stats is not None:
                self.stats.count("qos.expired")
            return 504, "application/json", json.dumps({"error": str(e)}).encode()
        finally:
            if self.stats is not None:
                dt_ms = (time.perf_counter() - t0) * 1e3
                self.stats.histogram(f"qos.latency_ms.{cls}", dt_ms)
                if door_tenant is not None:
                    # Per-tenant latency rides next to the per-class
                    # series (the hostile-neighbor bench's probe).
                    self.stats.histogram(
                        f"tenancy.latency_ms.{door_tenant}", dt_ms
                    )

    def _resolve_tenant(self, path: str, headers):
        """(tenant, index-tag): the deduped tenant extraction.  With
        isolation OFF this reproduces the pre-tenancy tagging exactly —
        tenant = the index name on /index/ paths, nothing otherwise,
        and no separate index tag.  With isolation ON it resolves
        through tenancy.resolve (header > [tenancy] map > index name >
        "default") and tags the index separately so the cost ledger
        keeps both dimensions."""
        index = tenancy_mod.index_of(path)
        if self.tenancy is None:
            return (index or None), None
        return self.tenancy.resolve(path, headers), (index or None)

    def _dispatch_route(self, method: str, path: str, params: dict, body: bytes,
                        headers: dict, deadline=None, span=None):
        matched_path = False
        for m, pattern, fn in self._routes:
            match = pattern.match(path)
            if match is None:
                continue
            matched_path = True
            if m != method:
                continue
            try:
                return fn(params=params, body=body, headers=headers,
                          deadline=deadline, span=span, **match.groupdict())
            except (qos.ShedError, qos.DeadlineExceeded):
                raise  # QoS outcomes map to 429/504 in dispatch()
            except HTTPError as e:
                return e.status, "application/json", json.dumps({"error": e.message}).encode()
            except errors.ErrIndexNotFound as e:
                return 404, "application/json", json.dumps({"error": str(e)}).encode()
            except errors.ErrFrameNotFound as e:
                return 404, "application/json", json.dumps({"error": str(e)}).encode()
            except (errors.ErrIndexExists, errors.ErrFrameExists) as e:
                return 409, "application/json", json.dumps({"error": str(e)}).encode()
            except (PilosaError, pql.ParseError, ValueError, TypeError) as e:
                return 400, "application/json", json.dumps({"error": str(e)}).encode()
            except Exception as e:  # internal error
                traceback.print_exc()
                return 500, "application/json", json.dumps({"error": str(e)}).encode()
        if matched_path:
            return 405, "text/plain", b"method not allowed"
        return 404, "text/plain", b"not found"

    # -- helpers --------------------------------------------------------

    @staticmethod
    def _json(obj, status=200):
        return status, "application/json", (json.dumps(obj) + "\n").encode()

    @staticmethod
    def _wants_protobuf(headers) -> bool:
        return PROTOBUF in headers.get("accept", "")

    @staticmethod
    def _sends_protobuf(headers) -> bool:
        return PROTOBUF in headers.get("content-type", "")

    @staticmethod
    def _param(params, name, default=None):
        v = params.get(name)
        return v[0] if v else default

    def _frag(self, params):
        index = self._param(params, "index")
        frame = self._param(params, "frame")
        view = self._param(params, "view", VIEW_STANDARD)
        slice_i = int(self._param(params, "slice", 0))
        frag = self.holder.fragment(index, frame, view, slice_i)
        if frag is None:
            raise HTTPError(404, "fragment not found")
        return frag

    # -- root / misc -----------------------------------------------------

    # WebUI embed (reference: webui/ served via statik, handler.go:132-145).
    _WEBUI_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "webui")
    _WEBUI_TYPES = {".html": "text/html", ".js": "application/javascript", ".css": "text/css",
                    ".svg": "image/svg+xml", ".png": "image/png"}

    def get_root(self, headers=None, **kw):
        # Browsers get the console; API clients keep the plain-text banner.
        if headers and "text/html" in (headers.get("accept") or ""):
            try:
                return self._webui_file("index.html")
            except HTTPError:
                pass  # bundle missing: the banner is a safer answer than 404
        return (
            200,
            "text/plain",
            b"Welcome. pilosa-tpu is running. POST PQL to /index/{index}/query.\n",
        )

    def get_webui_asset(self, file=None, **kw):
        if not file or "/" in file or file.startswith("."):
            raise HTTPError(404, "not found")
        return self._webui_file(os.path.join("assets", file))

    def _webui_file(self, rel: str):
        path = os.path.join(self._WEBUI_DIR, rel)
        try:
            with open(path, "rb") as f:
                body = f.read()
        except OSError:
            raise HTTPError(404, "not found")
        ctype = self._WEBUI_TYPES.get(os.path.splitext(rel)[1], "application/octet-stream")
        return 200, ctype, body

    def get_version(self, **kw):
        return self._json({"version": self.version})

    def get_hosts(self, **kw):
        nodes = self.cluster.nodes if self.cluster else []
        return self._json([n.to_json() for n in nodes])

    def get_schema(self, **kw):
        return self._json({"indexes": self.holder.schema()})

    def get_status(self, **kw):
        status = {
            "host": self.host,
            "state": "UP",
            "cluster": self.cluster.status_json() if self.cluster else {"nodes": []},
            "indexes": self.holder.schema(),
        }
        return self._json({"status": status})

    def get_slices_max(self, params=None, headers=None, **kw):
        m = self.holder.max_slices()
        if headers and self._wants_protobuf(headers):
            return 200, PROTOBUF, wire.encode_max_slices_response(m)
        inverse = self._param(params or {}, "inverse") == "true"
        if inverse:
            m = self.holder.max_inverse_slices()
        return self._json({"maxSlices": m})

    def get_replica_health(self, **kw):
        """Replica-router health probe: a 200 here restores an
        unhealthy group in the router's table (the lockstep front end
        serves the same route, answering 503 while degraded).  The
        reported ``appliedSeq`` is the catch-up trigger: a live group
        behind the router's WAL head gets the missed suffix replayed
        before it rejoins the read rotation."""
        out = {"group": self.group, "state": "UP"}
        if self.applied_seq is not None:
            out["appliedSeq"] = self.applied_seq.value
        return self._json(out)

    def get_replica_digest(self, **kw):
        """The group's content digest (replica/digest.py): schema plus a
        per-(index, frame, view, slice) fragment-checksum tree — what
        the router's resync diff and the anti-entropy sweep compare.
        Pure function of (schema, logical bits), so two groups that
        applied the same writes answer byte-identically."""
        from pilosa_tpu.replica.digest import holder_digest

        out = holder_digest(self.holder)
        if self.applied_seq is not None:
            out["appliedSeq"] = self.applied_seq.value
        return self._json(out)

    def post_replica_seed_seq(self, body=b"", **kw):
        """Resync handoff: adopt the donor's applied sequence after a
        fragment-level resync made this group's bytes match the donor's
        as of that sequence.  Monotonic (AppliedSeq.note never
        regresses), so a stray replayed seed is harmless."""
        try:
            seq = int((json.loads(body or b"{}") or {}).get("seq", 0))
        except (ValueError, TypeError):
            raise HTTPError(400, "bad seq")
        if seq <= 0:
            raise HTTPError(400, "seq must be positive")
        if self.applied_seq is None:
            raise HTTPError(409, "group has no applied-sequence tracking")
        self.applied_seq.note(seq)
        return self._json({"appliedSeq": self.applied_seq.value})

    def post_fragment_import_roaring(self, params=None, body=b"", **kw):
        """Receiving half of the resync fragment stream: replace one
        fragment wholesale from a serialized roaring payload, delivered
        in CRC-framed chunks so a killed transfer RESUMES instead of
        restarting.

        Protocol (query params): ``index/frame/view/slice`` name the
        fragment, ``total`` and ``crc`` (crc32 of the complete payload)
        identify the transfer, ``off`` is this chunk's byte offset.  A
        chunk whose ``off`` does not match the staged size answers 409
        with ``{"staged": n}`` so the sender resumes from ``n`` (an
        idempotent re-send of an already-staged chunk included);
        ``probe=1`` asks where the transfer stands without sending
        bytes.  A different (total, crc) for the same fragment restarts
        the transfer.  Once the staged bytes reach ``total`` and the
        CRC matches, the fragment (created along with its index, frame,
        and view when missing — the blank-group path) is replaced via
        ``read_from``, which bumps its generation so qcache entries and
        warm serve state invalidate exactly like any other write.
        ``total=0`` clears the fragment (the donor no longer holds it).
        Applying the same payload twice converges to the same bytes —
        the whole stream is idempotent."""
        params = params or {}
        index = self._param(params, "index")
        frame_name = self._param(params, "frame")
        view_name = self._param(params, "view", VIEW_STANDARD)
        slice_i = int(self._param(params, "slice", 0))
        off = int(self._param(params, "off", 0))
        total = int(self._param(params, "total", 0))
        crc = int(self._param(params, "crc", 0))
        probe = self._param(params, "probe") == "1"
        if not index or not frame_name:
            raise HTTPError(400, "index and frame required")
        if total < 0 or off < 0:
            raise HTTPError(400, "bad off/total")
        key = (index, frame_name, view_name, slice_i)
        with self._resync_mu:
            st = self._resync_staging.get(key)
            if st is not None and (st["total"] != total or st["crc"] != crc):
                # A different payload for this fragment: the previous
                # transfer is dead — restart.
                self._resync_staging.pop(key, None)
                st = None
            if probe:
                return self._json({"staged": len(st["buf"]) if st else 0})
            if st is None:
                if off != 0:
                    return self._json({"staged": 0}, status=409)
                st = {"total": total, "crc": crc, "buf": bytearray()}
                self._resync_staging[key] = st
            buf = st["buf"]
            if off != len(buf):
                return self._json({"staged": len(buf)}, status=409)
            buf += body
            if len(buf) > total:
                self._resync_staging.pop(key, None)
                raise HTTPError(409, "chunk overruns declared total")
            if len(buf) < total:
                return self._json({"staged": len(buf)})
            self._resync_staging.pop(key, None)
            data = bytes(buf)
        if zlib.crc32(data) != crc:
            raise HTTPError(409, "payload crc mismatch; transfer restarted")
        idx = self.holder.create_index_if_not_exists(index)
        frame = idx.create_frame_if_not_exists(frame_name)
        view = frame.create_view_if_not_exists(view_name)
        frag = view.create_fragment_if_not_exists(slice_i)
        if total == 0:
            # Clear: replace with an empty bitmap's serialized form.
            from pilosa_tpu import roaring

            empty = io.BytesIO()
            roaring.Bitmap().write_to(empty)
            data = empty.getvalue()
        frag.read_from(data)
        if self.executor is not None:
            # Warm device state for the frame predates the restore.
            self.executor.drop_frame_state(index, frame_name)
        if self.stats is not None:
            self.stats.count("replica.fragment_restores")
        return self._json({"applied": True, "checksum": frag.checksum().hex()})

    def get_expvar(self, **kw):
        stats = {}
        if self.stats is not None and hasattr(self.stats, "snapshot"):
            self._publish_shard_gauge()
            # One consistent snapshot under one short lock hold (the
            # striped client drains every write shard in the same hold).
            stats = self.stats.snapshot()
        return self._json(stats)

    def _publish_shard_gauge(self) -> None:
        """Pull-model gauge: live stats write shards at scrape time."""
        shard_count = getattr(self.stats, "shard_count", None)
        if shard_count is not None:
            self.stats.gauge("stats.shards", float(shard_count()))

    def get_debug_traces(self, params=None, **kw):
        """Finished request traces, newest-first (bounded ring).
        ``?min-ms=`` filters by total duration, ``?limit=`` caps the
        page (default 64).  Malformed or out-of-range filter values
        clamp to their defaults instead of 400ing — a debug endpoint a
        dashboard polls must never fail on a mistyped filter."""
        if self.tracer is None:
            return self._json({"traces": []})
        params = params or {}
        from pilosa_tpu import metrics as metrics_mod

        min_ms = metrics_mod.clamp_float(self._param(params, "min-ms"), 0.0)
        limit = metrics_mod.clamp_int(self._param(params, "limit"), 64, lo=0)
        return self._json(
            {"traces": self.tracer.traces_json(min_ms=min_ms, limit=limit)}
        )

    def get_debug_costs(self, params=None, **kw):
        """The per-fingerprint cost ledger (costs.CostLedger snapshot):
        EWMA cost/bandwidth per (index, frame, fingerprint, lane),
        highest cost first.  ``?limit=`` caps the page."""
        from pilosa_tpu import metrics as metrics_mod

        limit = metrics_mod.clamp_int(
            self._param(params or {}, "limit"), 0, lo=0
        )
        if self.costs is None:
            return self._json({"cap": 0, "alpha": 0.0, "entries": []})
        return self._json(self.costs.snapshot(limit=limit))

    def get_debug_planner(self, params=None, **kw):
        """The planner's decision state (planner.Planner snapshot):
        per-(index, fingerprint) chosen lane, confidence, consult/decided
        counts, and win/loss tallies joined with the per-lane ledger
        estimates, most-consulted first.  ``?limit=`` caps the page."""
        from pilosa_tpu import metrics as metrics_mod

        limit = metrics_mod.clamp_int(
            self._param(params or {}, "limit"), 0, lo=0
        )
        if self.planner is None:
            return self._json({"lanes": [], "keys": []})
        return self._json(self.planner.snapshot(limit=limit))

    def get_debug_tenants(self, **kw):
        """Per-tenant isolation state: fair-share door accounting
        (inflight / share / debt / admitted / shed per QoS class),
        qcache resident bytes + quota, ingest pacer buckets, and the
        cost-ledger billing aggregate.  ``enabled: false`` with no rows
        when isolation is off."""
        if self.tenancy is None:
            return self._json({"enabled": False, "tenants": {}})
        tenants: dict = {}
        if self.admission is not None:
            for t, row in self.admission.tenants_snapshot().items():
                tenants.setdefault(t, {}).update(row)
        qc = getattr(self.executor, "qcache", None)
        if qc is not None:
            for t, nbytes in qc.tenant_bytes_snapshot().items():
                row = tenants.setdefault(t, {})
                row["qcacheBytes"] = nbytes
                row["qcacheQuota"] = self.tenancy.qcache_quota(t, qc.max_bytes)
        if self.costs is not None:
            for t, agg in self.costs.by_tenant().items():
                tenants.setdefault(t, {})["ledger"] = agg
        if self.tenancy.pacer is not None:
            for t, row in self.tenancy.pacer.snapshot().items():
                tenants.setdefault(t, {})["ingest"] = row
        return self._json({
            "enabled": True,
            "defaultWeight": self.tenancy.default_weight,
            "tenants": tenants,
        })

    def get_metrics(self, **kw):
        """Prometheus text exposition of the whole stats registry
        (metrics.render): every counter/gauge/histogram the expvar
        client holds, names mapped mechanically from the COUNTERS.md
        registry (the stats-registry analysis rule gates the mapping)."""
        from pilosa_tpu import metrics as metrics_mod
        from pilosa_tpu.analysis import lockcheck

        if self.stats is not None:
            # Refresh the named-global gauges (parse memo & friends) at
            # scrape time — they are pull-model state, not event counters.
            lockcheck.publish_global_stats(self.stats)
            self._publish_shard_gauge()
        # render() reads one snapshot_typed() — the striped client
        # drains and renders under a single lock hold, so a scrape is
        # consistent against concurrent mutation by construction.
        text = metrics_mod.render(self.stats) if self.stats is not None else ""
        return 200, metrics_mod.CONTENT_TYPE, text.encode("utf-8")

    def get_pprof(self, path="", params=None, **kw):
        """/debug/pprof with net/http/pprof semantics (handler.go:99):
        the default payload is a gzipped pprof protobuf Profile that
        ``go tool pprof`` consumes; ``?debug=1`` returns the text form.

        Routes: /debug/pprof/goroutine (thread profile — one sample per
        live thread), /debug/pprof/profile?seconds=N (sampling CPU
        profile), bare /debug/pprof (thread profile)."""
        from pilosa_tpu import pprof as pprof_mod

        params = params or {}
        kind = (path or "").rsplit("/", 1)[-1]
        if self._param(params, "debug"):
            return 200, "text/plain", pprof_mod.text_threads().encode()
        if kind == "profile":
            try:
                seconds = float(self._param(params, "seconds") or "5")
            except ValueError:
                raise HTTPError(400, "bad seconds")
            seconds = min(seconds, 120.0)
            body = pprof_mod.cpu_profile(seconds)
        else:  # goroutine analog (and the index default)
            body = pprof_mod.thread_profile()
        return 200, "application/octet-stream", body

    def post_profile_start(self, params=None, **kw):
        """Start a JAX/XLA device trace (the TPU-native analog of the
        reference's CPU-profile flags, cmd/server.go:47-62).  Trace files
        land in ``dir`` (default <data>/profiles) for TensorBoard."""
        import jax

        trace_dir = self._param(params or {}, "dir") or os.path.join(
            self.holder.path, "profiles"
        )
        if self._profiling:
            raise HTTPError(409, "profile already running")
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception as e:
            raise HTTPError(500, f"profiler: {e}")
        self._profiling = trace_dir
        return self._json({"tracing": trace_dir})

    def post_profile_stop(self, **kw):
        import jax

        if not self._profiling:
            raise HTTPError(409, "no profile running")
        try:
            jax.profiler.stop_trace()
        finally:
            trace_dir, self._profiling = self._profiling, None
        return self._json({"written": trace_dir})

    # -- index lifecycle --------------------------------------------------

    def get_indexes(self, **kw):
        return self._json({"indexes": self.holder.schema()})

    def get_index(self, index=None, **kw):
        idx = self.holder.index(index)
        if idx is None:
            raise errors.ErrIndexNotFound(index)
        return self._json({"index": idx.schema_json()})

    def post_index(self, index=None, body=b"", **kw):
        opts = {}
        if body:
            opts = (json.loads(body) or {}).get("options", {})
        self.holder.create_index(
            index,
            IndexOptions(
                column_label=opts.get("columnLabel", ""),
                time_quantum=opts.get("timeQuantum", ""),
            ),
        )
        if self.broadcaster is not None:
            self.broadcaster.create_index(index, opts)
        return self._json({})

    def delete_index(self, index=None, **kw):
        self.holder.delete_index(index)
        if self.executor is not None:
            # Reclaim warm device state eagerly (serve states, row pools,
            # Grams): validity tokens already prevent stale serving for a
            # recreated namesake, but the old state would otherwise pin
            # HBM until LRU churn evicts it.
            self.executor.drop_index_state(index)
        if self.broadcaster is not None:
            self.broadcaster.delete_index(index)
        return self._json({})

    def patch_index_time_quantum(self, index=None, body=b"", **kw):
        idx = self.holder.index(index)
        if idx is None:
            raise errors.ErrIndexNotFound(index)
        q = (json.loads(body) or {}).get("timeQuantum", "")
        idx.set_time_quantum(q)
        return self._json({})

    # -- frame lifecycle --------------------------------------------------

    def post_frame(self, index=None, frame=None, body=b"", **kw):
        idx = self.holder.index(index)
        if idx is None:
            raise errors.ErrIndexNotFound(index)
        opts = {}
        if body:
            opts = (json.loads(body) or {}).get("options", {})
        idx.create_frame(
            frame,
            FrameOptions(
                row_label=opts.get("rowLabel", ""),
                inverse_enabled=opts.get("inverseEnabled", False),
                cache_type=opts.get("cacheType", ""),
                cache_size=opts.get("cacheSize", 0),
                time_quantum=opts.get("timeQuantum", ""),
            ),
        )
        if self.broadcaster is not None:
            self.broadcaster.create_frame(index, frame, opts)
        return self._json({})

    def delete_frame(self, index=None, frame=None, **kw):
        idx = self.holder.index(index)
        if idx is None:
            raise errors.ErrIndexNotFound(index)
        idx.delete_frame(frame)
        if self.executor is not None:
            self.executor.drop_frame_state(index, frame)
        if self.broadcaster is not None:
            self.broadcaster.delete_frame(index, frame)
        return self._json({})

    def patch_frame_time_quantum(self, index=None, frame=None, body=b"", **kw):
        f = self.holder.frame(index, frame)
        if f is None:
            raise errors.ErrFrameNotFound(frame)
        q = (json.loads(body) or {}).get("timeQuantum", "")
        f.set_time_quantum(q)
        return self._json({})

    def get_frame_views(self, index=None, frame=None, **kw):
        f = self.holder.frame(index, frame)
        if f is None:
            raise errors.ErrFrameNotFound(frame)
        return self._json({"views": sorted(f.views.keys())})

    # -- query (handler.go:179-243) ----------------------------------------

    def post_query(self, index=None, params=None, body=b"", headers=None, deadline=None, span=None, **kw):
        headers = headers or {}
        params = params or {}
        if self._sends_protobuf(headers):
            req = wire.decode_query_request(body)
            query_str = req["query"]
            slices = req["slices"] or None
            column_attrs = req["column_attrs"]
            remote = req["remote"]
        else:
            query_str = body.decode()
            slices_param = self._param(params, "slices")
            slices = [int(s) for s in slices_param.split(",")] if slices_param else None
            column_attrs = self._param(params, "columnAttrs") == "true"
            remote = self._param(params, "remote") == "true"

        # Per-request qcache bypass (A/B measurement, stale-read
        # debugging): the request neither reads nor stores an entry.
        no_cache = (headers.get(qcache_mod.NO_CACHE_HEADER.lower(), "") or "").strip().lower() in (
            "1", "true", "yes"
        )
        opt = ExecOptions(remote=remote, deadline=deadline, no_cache=no_cache,
                          span=span)
        if self.planner is not None and not remote:
            # Front-door planner consultation (remote hops carry no plan:
            # the originating door already decided for the whole query).
            # Keyed on the decoded query text so protobuf and JSON
            # transports share one fingerprint.
            opt.plan = self.planner.plan_for(index, query_str.encode())
        try:
            results = self.executor.execute(index, query_str, slices=slices, opt=opt)
        except qos.DeadlineExceeded:
            raise  # 504, not the 400 a PilosaError would map to
        except (PilosaError, pql.ParseError) as e:
            if self._wants_protobuf(headers):
                return 400, PROTOBUF, wire.encode_query_response(err=str(e))
            return 400, "application/json", json.dumps({"error": str(e)}).encode()

        column_attr_sets = []
        if column_attrs:
            idx = self.holder.index(index)
            seen = set()
            for r in results:
                if isinstance(r, QueryBitmap):
                    for col in r.bits():
                        if col in seen:
                            continue
                        seen.add(col)
                        attrs = idx.column_attr_store.attrs(col)
                        if attrs:
                            column_attr_sets.append((col, attrs))

        if self._wants_protobuf(headers):
            return 200, PROTOBUF, wire.encode_query_response(
                results=results, column_attr_sets=column_attr_sets
            )
        out = {"results": [result_to_json(r) for r in results]}
        if column_attr_sets:
            out["columnAttrSets"] = [
                {"id": id, "attrs": attrs} for id, attrs in column_attr_sets
            ]
        return self._json(out)

    # -- streaming columnar ingest (the bulk-write front door) --------------

    def _ingest_apply(self, key, rows, cols, deadline):
        """One decoded chunk -> the batched set_bits path (+ executor
        dirty-row notes so warm serve state patches, not rebuilds)."""
        from pilosa_tpu import ingest as ingest_mod

        index, fname = key
        frame = self.holder.frame(index, fname)
        if frame is None:
            # Deleted mid-transfer: deterministic 404 for this chunk.
            raise errors.ErrFrameNotFound(fname)
        return ingest_mod.apply_columnar(
            frame, rows, cols, executor=self.executor, index=index,
            deadline=deadline,
        )

    def _ingest_complete(self, key) -> None:
        """Import-parity hook: transfer done -> rank caches fresh NOW."""
        from pilosa_tpu import ingest as ingest_mod

        index, fname = key
        frame = self.holder.frame(index, fname)
        if frame is not None:
            ingest_mod.recalc_frame_caches(frame)

    def _bulk_apply(self, key, rows, cols, deadline):
        """One decoded bulk chunk -> device build + overlay commit
        (pilosa_tpu/bulk): the chunk's columns sort/segment/scatter into
        word planes on the executor's engine and land as pending dense
        overlays — no roaring container churn on the ingest path."""
        from pilosa_tpu.bulk import ingress

        index, fname = key
        frame = self.holder.frame(index, fname)
        if frame is None:
            raise errors.ErrFrameNotFound(fname)
        engine = getattr(self.executor, "engine", None)
        return ingress.apply_bulk(
            frame, rows, cols, engine=engine, executor=self.executor,
            index=index, deadline=deadline,
            batch_slices=self.bulk_batch_slices, stats=self.stats,
        )

    def _bulk_complete(self, key) -> None:
        """Bulk transfer done: rankings fresh (import parity), then the
        opportunistic overlay drain under the configured budget."""
        from pilosa_tpu.bulk import ingress

        index, fname = key
        frame = self.holder.frame(index, fname)
        if frame is not None:
            ingress.complete_bulk(frame, self.bulk_materialize_budget_ms)

    def post_frame_ingest(self, index=None, frame=None, params=None, body=b"",
                          headers=None, deadline=None, **kw):
        """Streaming columnar bulk ingest: ``(row, col)`` column chunks
        applied straight into the batched write path.

        Wire: each POST carries one chunk of a transfer identified by
        query params ``total`` (whole payload bytes) + ``crc`` (crc32
        of the whole payload); ``off`` is this chunk's byte offset and
        must equal the applied frontier (a re-send below it acks
        idempotently, a gap answers 409 + ``{"staged": n}`` so the
        sender resumes); ``ccrc`` is the chunk's own crc32, verified
        before any bit is touched; ``probe=1`` asks where the transfer
        stands.  Chunk payloads are packed-uint64 frames
        (``PI64 | u32 n | rows | cols``) or — with an Arrow content
        type and pyarrow importable — Arrow IPC record batches with
        uint64 ``row``/``col`` columns.  QoS classifies the route as a
        write, so each chunk passes the write-class admission door
        (ingest bursts backpressure instead of starving reads) and the
        replica router sequences + WAL-logs chunks like any other
        write — replay is idempotent.  On completion the frame's rank
        caches recalculate immediately (import parity)."""
        return self._stream_door(
            self._ingestor, index, frame, params, body, headers, deadline
        )

    def post_frame_bulk(self, index=None, frame=None, params=None, body=b"",
                        headers=None, deadline=None, **kw):
        """Device-first bulk build door: the SAME chunk/resume/CRC wire
        as ``POST .../ingest`` (probe, offsets, 409 + staged, per-chunk
        ccrc, PI64 or Arrow IPC payloads), but each chunk's columns run
        the engine's jitted sort/segment/scatter build and commit
        packed word planes as pending fragment overlays — roaring
        containers and rank caches materialize lazily on first
        snapshot/sync/digest touch, or under the
        ``[bulk] materialize-budget-ms`` drain at completion.  QoS
        classifies the route as a write; the replica router sequences
        and WAL-logs chunks like any other write (replay idempotent —
        the overlay OR converges)."""
        return self._stream_door(
            self._bulk_ingestor, index, frame, params, body, headers, deadline
        )

    def _stream_door(self, ingestor, index, frame, params, body, headers,
                     deadline):
        """Shared chunk-wire plumbing for the streamed and bulk doors:
        parse the transfer params, answer probes, push the chunk."""
        headers = headers or {}
        params = params or {}
        idx = self.holder.index(index)
        if idx is None:
            raise errors.ErrIndexNotFound(index)
        f = idx.frame(frame)
        if f is None:
            raise errors.ErrFrameNotFound(frame)
        try:
            off = int(self._param(params, "off", 0))
            total = int(self._param(params, "total", 0))
            crc = int(self._param(params, "crc", 0))
            ccrc_s = self._param(params, "ccrc")
            ccrc = int(ccrc_s) if ccrc_s is not None else None
        except (TypeError, ValueError):
            raise HTTPError(400, "bad off/total/crc/ccrc")
        from pilosa_tpu import ingest as ingest_mod

        key = (index, frame)
        if self._param(params, "probe") == "1":
            return self._json(ingestor.probe(key, total, crc))
        # Per-tenant bandwidth pacing ([tenancy] ingest-bytes-per-s):
        # a chunk past the tenant's token-bucket share answers 429 +
        # Retry-After BEFORE it stages — a hostile backfill backs off
        # while other tenants' chunks keep clearing at their share.
        if (
            self.tenancy is not None
            and self.tenancy.pacer is not None
            and body
        ):
            tenant = self.tenancy.resolve_for_index(index, headers)
            wait = self.tenancy.pacer.admit(tenant, len(body))
            if wait > 0.0:
                if self.stats is not None:
                    self.stats.count(f"tenancy.ingest_shed.{tenant}")
                raise qos.ShedError(
                    f"tenant {tenant!r} over its ingest bandwidth share;"
                    f" retry after {wait:.3f}s",
                    retry_after=wait,
                )
            if self.stats is not None:
                self.stats.count(f"tenancy.ingest_bytes.{tenant}", len(body))
        arrow = "arrow" in (headers.get("content-type") or "")
        try:
            out = ingestor.chunk(
                key, off, total, crc, body, chunk_crc=ccrc, arrow=arrow,
                deadline=deadline,
            )
        except ingest_mod.IngestError as e:
            return self._json(
                {"error": str(e), "staged": e.staged}, status=e.status
            )
        return self._json(out)

    # -- import (handler.go:900-978) ---------------------------------------

    def post_import(self, body=b"", headers=None, **kw):
        req = wire.decode_import_request(body)
        index_name, frame_name = req["index"], req["frame"]
        slice_i = req["slice"]
        idx = self.holder.index(index_name)
        if idx is None:
            raise errors.ErrIndexNotFound(index_name)
        frame = idx.frame(frame_name)
        if frame is None:
            raise errors.ErrFrameNotFound(frame_name)
        # Reject imports for slices this node doesn't own (412, handler.go:936).
        if self.cluster is not None and self.host:
            if not self.cluster.owns_fragment(self.host, index_name, slice_i):
                raise HTTPError(412, f"host does not own slice {slice_i}")
        timestamps = [
            datetime.utcfromtimestamp(t) if t else None for t in req["timestamps"]
        ] or None
        frame.import_bits(req["rowIDs"], req["columnIDs"], timestamps)
        return self._json({})

    # -- export (handler.go:990-1030) --------------------------------------

    def get_export(self, params=None, headers=None, **kw):
        """Fragment contents as CSV (default) or, with ``format=arrow``,
        as an Arrow IPC stream of uint64 ``row``/``col`` columns — the
        exact schema the bulk/ingest doors accept, so an export can be
        re-ingested byte-identically.  Both formats read the fragment's
        merged dense view (``export_pairs``): a pending bulk overlay is
        visible without materializing roaring containers."""
        params = params or {}
        index = self._param(params, "index")
        frame = self._param(params, "frame")
        view = self._param(params, "view", VIEW_STANDARD)
        slice_i = int(self._param(params, "slice", 0))
        frag = self.holder.fragment(index, frame, view, slice_i)
        if frag is None:
            raise HTTPError(404, "fragment not found")
        fmt = self._param(params, "format", "csv")
        if fmt == "arrow":
            from pilosa_tpu import ingest as ingest_mod
            from pilosa_tpu.bulk import egress

            try:
                payload = egress.export_fragment_arrow(frag, stats=self.stats)
            except ingest_mod.IngestError as e:
                return self._json({"error": str(e)}, status=e.status)
            return 200, ingest_mod.ARROW_CONTENT_TYPE, payload
        if fmt != "csv":
            raise HTTPError(400, f"unknown export format {fmt!r}")
        out = io.StringIO()
        rows, cols = frag.export_pairs()
        for r, c in zip(rows.tolist(), cols.tolist()):
            out.write(f"{r},{c}\n")
        return 200, "text/csv", out.getvalue().encode()

    # -- fragment data / sync (handler.go:1053-1178) ------------------------

    def get_fragment_data(self, params=None, **kw):
        frag = self._frag(params or {})
        buf = io.BytesIO()
        frag.write_to(buf)
        return 200, "application/octet-stream", buf.getvalue()

    def post_fragment_data(self, params=None, body=b"", **kw):
        params = params or {}
        index = self._param(params, "index")
        frame_name = self._param(params, "frame")
        view_name = self._param(params, "view", VIEW_STANDARD)
        slice_i = int(self._param(params, "slice", 0))
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            raise HTTPError(404, "frame not found")
        view = frame.create_view_if_not_exists(view_name)
        frag = view.create_fragment_if_not_exists(slice_i)
        frag.read_from(body)
        return self._json({})

    def get_fragment_blocks(self, params=None, **kw):
        frag = self._frag(params or {})
        return self._json(
            {"blocks": [{"id": bid, "checksum": chk.hex()} for bid, chk in frag.blocks()]}
        )

    def get_fragment_block_data(self, params=None, body=b"", headers=None, **kw):
        headers = headers or {}
        if body and self._sends_protobuf(headers):
            req = wire.decode_block_data_request(body)
            index, frame = req["index"], req["frame"]
            view, slice_i, block = req["view"], req["slice"], req["block"]
        else:
            params = params or {}
            index = self._param(params, "index")
            frame = self._param(params, "frame")
            view = self._param(params, "view", VIEW_STANDARD)
            slice_i = int(self._param(params, "slice", 0))
            block = int(self._param(params, "block", 0))
        frag = self.holder.fragment(index, frame, view, slice_i)
        if frag is None:
            raise HTTPError(404, "fragment not found")
        rows, cols = frag.block_data(block)
        payload = wire.encode_block_data_response(rows.tolist(), cols.tolist())
        return 200, PROTOBUF, payload

    def post_fragment_block_diff(self, params=None, body=b"", **kw):
        """Apply a sync diff directly to a fragment (any view) — the
        receiving half of the anti-entropy push."""
        frag = self._frag(params or {})
        set_rows, set_cols, clear_rows, clear_cols = wire.decode_block_diff(body)
        for r, c in zip(set_rows, set_cols):
            frag.set_bit(r, c)
        for r, c in zip(clear_rows, clear_cols):
            frag.clear_bit(r, c)
        return self._json({})

    def get_fragment_nodes(self, params=None, **kw):
        params = params or {}
        index = self._param(params, "index")
        slice_i = int(self._param(params, "slice", 0))
        if self.cluster is None:
            return self._json([{"host": self.host, "internalHost": "", "state": "UP"}])
        nodes = self.cluster.fragment_nodes(index, slice_i)
        return self._json([n.to_json() for n in nodes])

    # -- attr diff (handler.go:472-518, 735-782) -----------------------------

    def post_index_attr_diff(self, index=None, body=b"", **kw):
        idx = self.holder.index(index)
        if idx is None:
            raise errors.ErrIndexNotFound(index)
        return self._attr_diff(idx.column_attr_store, body)

    def post_frame_attr_diff(self, index=None, frame=None, body=b"", **kw):
        f = self.holder.frame(index, frame)
        if f is None:
            raise errors.ErrFrameNotFound(frame)
        return self._attr_diff(f.row_attr_store, body)

    def _attr_diff(self, store, body: bytes):
        # Requester posts its block checksums; we reply with our attrs for
        # every block where our data differs (or they lack the block), and
        # the requester merges what it's missing (attr.go:394-428).
        req = json.loads(body or b"{}")
        remote = {b["id"]: bytes.fromhex(b["checksum"]) for b in req.get("blocks", [])}
        ids = [bid for bid, chk in store.blocks() if remote.get(bid) != chk]
        attrs = {}
        for bid in sorted(ids):
            for id, a in store.block_data(bid).items():
                attrs[str(id)] = a
        return self._json({"attrs": attrs})

    # -- frame restore (handler.go:1184-1271) --------------------------------

    def post_frame_restore(self, index=None, frame=None, params=None, **kw):
        params = params or {}
        src_host = self._param(params, "host")
        if not src_host:
            raise HTTPError(400, "host required")
        if self.client_factory is None:
            raise HTTPError(500, "no client factory configured")
        client = self.client_factory(src_host)
        f = self.holder.frame(index, frame)
        if f is None:
            raise errors.ErrFrameNotFound(frame)
        max_slices = client.max_slices()
        max_slice = max_slices.get(index, 0)
        for view_name in client.frame_views(index, frame):
            view = f.create_view_if_not_exists(view_name)
            for slice_i in range(max_slice + 1):
                data = client.fragment_data(index, frame, view_name, slice_i)
                if data is None:
                    continue
                frag = view.create_fragment_if_not_exists(slice_i)
                frag.read_from(data)
        return self._json({})


class _HTTPRequestHandler(BaseHTTPRequestHandler):
    handler: Handler = None  # set by serve()
    protocol_version = "HTTP/1.1"

    def _run(self, method: str):
        parsed = urlparse(self.path)
        params = parse_qs(parsed.query)
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        headers = {k.lower(): v for k, v in self.headers.items()}
        out = self.handler.dispatch(method, parsed.path, params, body, headers)
        status, ctype, payload = out[:3]
        extra = out[3] if len(out) > 3 else {}
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in extra.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        self._run("GET")

    def do_POST(self):
        self._run("POST")

    def do_DELETE(self):
        self._run("DELETE")

    def do_PATCH(self):
        self._run("PATCH")

    def log_message(self, fmt, *args):  # quiet by default
        pass


# Default connection-worker pool size: enough for every in-tree client
# rig (benches cap at 16 client threads) with headroom for keep-alive
# connections that pin a worker between requests.
DEFAULT_MAX_THREADS = 32

_POOL_STOP = object()


class PooledHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a BOUNDED connection worker pool.

    Accepted connections are queued to ``max_threads`` pre-spawned
    workers instead of spawning one thread per connection; a full queue
    waits ``overflow_wait_s`` then sheds the connection with a raw
    503 + Retry-After (the same contract the QoS door gives an admitted
    request, issued before a worker is ever consumed, so clients retry
    through the normal budget).  ``reuse_port=True`` sets SO_REUSEPORT
    before bind — the multi-process worker mode on GIL builds runs N
    such servers on one port and lets the kernel spread accepts.
    """

    def __init__(self, addr, cls, max_threads: int = DEFAULT_MAX_THREADS,
                 overflow_wait_s: float = 0.05, retry_after_s: float = 0.25,
                 reuse_port: bool = False, stats=None):
        self._reuse_port = reuse_port
        self.pool_stats = stats
        self._overflow_wait_s = overflow_wait_s
        self._retry_after = max(1, int(retry_after_s + 0.999))
        self._max_threads = max(1, int(max_threads))
        self._conn_q: "queue.Queue" = queue.Queue(maxsize=self._max_threads * 2)
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"http-pool-{i}")
            for i in range(self._max_threads)
        ]
        super().__init__(addr, cls)
        for t in self._workers:
            t.start()
        stats = self.pool_stats
        if stats is not None:
            stats.gauge("server.pool.workers", float(self._max_threads))

    def server_bind(self):
        if self._reuse_port:
            if not hasattr(socket, "SO_REUSEPORT"):
                raise OSError("SO_REUSEPORT unsupported on this platform")
            self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()

    def _worker(self) -> None:
        while True:
            item = self._conn_q.get()
            if item is _POOL_STOP:
                return
            request, client_address = item
            # The mixin's per-connection body: finish_request +
            # handle_error + shutdown_request, minus the thread spawn.
            self.process_request_thread(request, client_address)

    def process_request(self, request, client_address):
        try:
            self._conn_q.put((request, client_address),
                             timeout=self._overflow_wait_s)
        except queue.Full:
            self._shed(request)

    def _shed(self, request) -> None:
        stats = self.pool_stats
        if stats is not None:
            stats.count("server.pool.shed")
            stats.gauge("server.pool.queue_depth", float(self._conn_q.qsize()))
        try:
            request.sendall(
                (
                    "HTTP/1.1 503 Service Unavailable\r\n"
                    f"Retry-After: {self._retry_after}\r\n"
                    "Content-Length: 0\r\nConnection: close\r\n\r\n"
                ).encode()
            )
        except OSError:
            pass
        self.shutdown_request(request)

    def server_close(self):
        super().server_close()
        # Unblock every worker, then close any connection still queued.
        for _ in self._workers:
            self._conn_q.put(_POOL_STOP)
        while True:
            try:
                item = self._conn_q.get_nowait()
            except queue.Empty:
                break
            if item is not _POOL_STOP:
                self.shutdown_request(item[0])


def serve(handler: Handler, host: str = "127.0.0.1", port: int = 0,
          max_threads: int = DEFAULT_MAX_THREADS, reuse_port: bool = False,
          retry_after_s: float = 0.25) -> ThreadingHTTPServer:
    """Start an HTTP server for the handler; returns the (running) server.

    ``max_threads`` bounds the connection worker pool (0 = the legacy
    unbounded thread-per-connection server).
    """
    cls = type("BoundHandler", (_HTTPRequestHandler,), {"handler": handler})
    if max_threads and max_threads > 0:
        httpd: ThreadingHTTPServer = PooledHTTPServer(
            (host, port), cls, max_threads=max_threads,
            retry_after_s=retry_after_s, reuse_port=reuse_port,
            stats=getattr(handler, "stats", None),
        )
    else:
        httpd = ThreadingHTTPServer((host, port), cls)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd
