"""HTTP API, client, and server composition.

Reference analogs: handler.go (route table + codecs), client.go (full
HTTP client), server.go (wiring + background loops).
"""

from pilosa_tpu.server.handler import Handler  # noqa: F401
from pilosa_tpu.server.server import Server  # noqa: F401
