"""HTTP client mirroring the full API (reference analog: client.go, 1053 LoC).

Used by: remote query execution (executor mapReduce), write forwarding,
bulk import (grouping bits by slice and POSTing protobuf to every owner
node, client.go:304-390), backup/restore streaming, fragment block sync,
attr-diff sync, and the ctl tools.
"""

from __future__ import annotations

import json
import os
import random
import time
import urllib.error
import urllib.request
from typing import Any, Optional, Sequence

import numpy as np

from pilosa_tpu import pql, wire
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.executor import QueryBitmap
from pilosa_tpu.ops.bitwise import pack_positions
from pilosa_tpu.pilosa import SLICE_WIDTH, PilosaError
from pilosa_tpu.qcache import NO_CACHE_HEADER
from pilosa_tpu.qos import DEADLINE_HEADER
from pilosa_tpu.replica import GROUP_HEADER
from pilosa_tpu.trace import TRACE_HEADER, TRACE_SPANS_HEADER

PROTOBUF = "application/x-protobuf"

# Backoff cap when honoring a peer's Retry-After on 429/503 in the
# cluster fan-out: a peer advertising a long recovery must not stall a
# forwarded sub-request longer than this per attempt.
RETRY_AFTER_CAP_S = 2.0

# Decorrelated-jitter backoff floor between retry attempts (AWS
# architecture-blog discipline: each wait draws uniform(base, 3x the
# previous wait), so a retrying fleet spreads out instead of thundering
# back in lockstep).
RETRY_BASE_S = 0.05

# Default retry budget ([client] retry-budget): total EXTRA attempts a
# single logical request may spend across its lifetime.
DEFAULT_RETRY_BUDGET = 2


class ClientError(PilosaError):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class Client:
    def __init__(self, host: str, timeout: float = 30.0,
                 retry_budget: Optional[int] = None, stats=None):
        if "://" not in host:
            host = "http://" + host
        self.base = host.rstrip("/")
        self.timeout = timeout
        # Retry budget (ctor arg — the Server passes [client]
        # retry-budget — > env > default).  Budgeted retries fire ONLY
        # on 429/503 answers: both are door sheds in this stack
        # (admission/quorum refusal BEFORE execution), so retrying a
        # write is safe — a request that reached execution answers with
        # some other status and is never retried past its first byte of
        # effect.
        if retry_budget is None:
            retry_budget = int(
                os.environ.get(  # analysis-ok: env-knob-outside-config: client-side fallback for directly-constructed clients; the Server passes [client] config
                    "PILOSA_TPU_CLIENT_RETRY_BUDGET", str(DEFAULT_RETRY_BUDGET)
                )
            )
        self.retry_budget = max(0, retry_budget)
        self.stats = stats
        self._rng = random.Random()

    # -- low level -------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        content_type: str = "application/json",
        accept: str = "application/json",
        headers: Optional[dict] = None,
        timeout: Optional[float] = None,
        retries: Optional[int] = None,
        deadline=None,
        capture: Optional[dict] = None,
    ) -> tuple[int, bytes]:
        """One HTTP exchange; ``timeout`` overrides the constructor-wide
        default per request.

        RETRY BUDGET: a 429/503 answer — a door shed, issued BEFORE any
        execution, so safe to retry even for writes; a request that
        reached execution never answers 429/503 and is never retried
        past its first byte of effect — is retried up to ``retries``
        times (default: the client's ``retry_budget``; 0 disables).
        Each wait uses DECORRELATED JITTER (uniform between the base
        and 3x the previous wait, so a shedding server sees retries
        spread out, not a thundering herd), floored by the peer's
        ``Retry-After`` hint and capped at RETRY_AFTER_CAP_S.  The loop
        is DEADLINE-AWARE: a wait that could not finish inside the
        remaining budget returns the shed answer instead of sleeping
        through it.  Each retry counts ``client.retries``.

        ``capture`` (a dict) receives the final response's headers under
        ``"headers"`` — the trace hop reads X-Pilosa-Trace-Spans from
        it.  The SAME Request object serves every retry attempt, so a
        retried request keeps its identity (deadline budget and trace
        id headers included): the peer sees one request retried, never
        two distinct root spans."""
        if retries is None:
            retries = self.retry_budget
        req = urllib.request.Request(self.base + path, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", content_type)
        req.add_header("Accept", accept)
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        attempt = 0
        prev_wait = RETRY_BASE_S
        while True:
            try:
                with urllib.request.urlopen(
                    req, timeout=timeout if timeout is not None else self.timeout
                ) as resp:
                    if capture is not None:
                        capture["headers"] = resp.headers
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                status, payload, resp_headers = e.code, e.read(), e.headers
                if capture is not None:
                    capture["headers"] = resp_headers
            if status not in (429, 503) or attempt >= retries:
                return status, payload
            attempt += 1
            wait = self._rng.uniform(RETRY_BASE_S, prev_wait * 3.0)
            try:
                hint = float(resp_headers.get("Retry-After", "0"))
            except (TypeError, ValueError):
                hint = 0.0
            wait = min(max(wait, hint, 0.0), RETRY_AFTER_CAP_S)
            prev_wait = wait
            if deadline is not None:
                left = deadline.remaining_ms() / 1000.0
                if left <= wait:
                    return status, payload  # a retry could not finish in budget
            if self.stats is not None:
                self.stats.count("client.retries")
            time.sleep(wait)

    def _json(self, method: str, path: str, obj: Any = None) -> dict:
        body = json.dumps(obj).encode() if obj is not None else None
        status, payload = self._request(method, path, body)
        if status >= 400:
            msg = payload.decode(errors="replace")
            try:
                msg = json.loads(msg).get("error", msg)
            # analysis-ok: exception-hygiene: best-effort decode of an error payload; the real error raises on the next line
            except Exception:
                pass
            raise ClientError(status, msg)
        return json.loads(payload) if payload else {}

    # -- queries (client.go:38-120) ---------------------------------------

    def execute_query(
        self,
        index: str,
        query: str,
        slices: Optional[Sequence[int]] = None,
        column_attrs: bool = False,
        remote: bool = False,
        deadline=None,
        timeout: Optional[float] = None,
        no_cache: bool = False,
        trace_span=None,
    ) -> dict:
        """Execute PQL; returns the decoded QueryResponse dict.

        ``deadline`` (qos.Deadline) forwards the REMAINING budget to the
        peer as the X-Pilosa-Deadline-Ms hop header and tightens the
        socket timeout to match; a shed (429) or unavailable (503) peer
        is retried within the client's deadline-aware retry budget
        (decorrelated jitter, floored by Retry-After).  ``no_cache`` sets
        X-Pilosa-No-Cache so the peer's query result cache neither
        serves nor stores this request (A/B measurement, stale-read
        debugging).  ``trace_span`` (trace.Span) propagates the request
        trace across the hop: the trace id goes out in X-Pilosa-Trace
        (forcing the peer to trace), and the peer's span tree from the
        X-Pilosa-Trace-Spans response header is grafted under it.  The
        retry reuses the same Request object, so a retried hop keeps
        ONE trace identity — no duplicate root spans on the peer.
        """
        body = wire.encode_query_request(
            query, slices=list(slices or []), column_attrs=column_attrs, remote=remote
        )
        headers = {}
        if no_cache:
            headers[NO_CACHE_HEADER] = "1"
        if trace_span is not None:
            headers[TRACE_HEADER] = getattr(trace_span, "trace_id", "") or "1"
        if deadline is not None:
            headers[DEADLINE_HEADER] = deadline.header_value()
            if timeout is None:
                # Socket bound tracks the budget (+ slack for the 504
                # answer itself to travel back).
                timeout = min(self.timeout, deadline.remaining_ms() / 1000.0 + 1.0)
        capture: dict = {}
        status, payload = self._request(
            "POST", f"/index/{index}/query", body, content_type=PROTOBUF, accept=PROTOBUF,
            headers=headers, timeout=timeout, deadline=deadline,
            capture=capture,
        )
        if trace_span is not None and capture.get("headers") is not None:
            raw = capture["headers"].get(TRACE_SPANS_HEADER)
            if raw:
                try:
                    trace_span.graft(json.loads(raw))
                except ValueError:
                    pass  # a malformed header never fails the query
        if status >= 400:
            msg = payload.decode(errors="replace")
            try:
                msg = wire.decode_query_response(payload).get("err") or msg
            except ValueError:
                try:
                    msg = json.loads(msg).get("error", msg)
                # analysis-ok: exception-hygiene: best-effort decode of an error payload; the real error raises below
                except Exception:
                    pass
            raise ClientError(status, msg)
        resp = wire.decode_query_response(payload)
        if resp.get("err"):
            raise ClientError(status, resp["err"])
        # Replica attribution: which serving group (or "all", for a
        # router write fan-out) answered — absent off group-less hosts.
        if capture.get("headers") is not None:
            grp = capture["headers"].get(GROUP_HEADER)
            if grp:
                resp["group"] = grp
        return resp

    def execute_remote(
        self,
        index: str,
        query: "pql.Query",
        slices: Optional[Sequence[int]] = None,
        deadline=None,
        no_cache: bool = False,
        trace_span=None,
    ) -> list:
        """Forward a parsed query for remote execution; returns typed results
        (the client half of executor.go:1009-1091).  proto3 omits
        zero-valued fields, so each QueryResult is interpreted against its
        call's expected type, as the reference does (executor.go:1068-1085).
        """
        resp = self.execute_query(
            index, str(query), slices=slices, remote=True, deadline=deadline,
            no_cache=no_cache, trace_span=trace_span,
        )
        return [
            _result_from_wire(r, expect=c.name)
            for r, c in zip(resp["results"], query.calls)
        ]

    def execute_remote_call(
        self, index: str, call: "pql.Call", slices: Sequence[int], deadline=None,
        no_cache: bool = False, trace_span=None,
    ):
        results = self.execute_remote(
            index, pql.Query(calls=[call]), slices=slices, deadline=deadline,
            no_cache=no_cache, trace_span=trace_span,
        )
        return results[0]

    # -- schema (client.go:392-460) ----------------------------------------

    def schema(self) -> list[dict]:
        return self._json("GET", "/schema")["indexes"]

    def create_index(self, index: str, options: Optional[dict] = None) -> None:
        self._json("POST", f"/index/{index}", {"options": options or {}})

    def delete_index(self, index: str) -> None:
        self._json("DELETE", f"/index/{index}")

    def create_frame(self, index: str, frame: str, options: Optional[dict] = None) -> None:
        self._json("POST", f"/index/{index}/frame/{frame}", {"options": options or {}})

    def delete_frame(self, index: str, frame: str) -> None:
        self._json("DELETE", f"/index/{index}/frame/{frame}")

    def frame_views(self, index: str, frame: str) -> list[str]:
        return self._json("GET", f"/index/{index}/frame/{frame}/views")["views"]

    def max_slices(self, inverse: bool = False) -> dict[str, int]:
        suffix = "?inverse=true" if inverse else ""
        return self._json("GET", f"/slices/max{suffix}")["maxSlices"]

    def hosts(self) -> list[dict]:
        return self._json("GET", "/hosts")

    def status(self) -> dict:
        return self._json("GET", "/status")["status"]

    def replica_status(self) -> dict:
        """The replica router's live group table (/replica/status):
        per-group health/inflight/epoch plus the quorum flag."""
        return self._json("GET", "/replica/status")

    def version(self) -> str:
        return self._json("GET", "/version")["version"]

    # -- import (client.go:304-390) ----------------------------------------

    def import_bits(
        self,
        index: str,
        frame: str,
        bits: Sequence[tuple],
        fragment_nodes=None,
    ) -> None:
        """Group (row, col[, timestamp]) bits by slice and POST each group to
        every owner node (client.go:304-331)."""
        groups: dict[int, list[tuple]] = {}
        for bit in bits:
            slice_i = int(bit[1]) // SLICE_WIDTH
            groups.setdefault(slice_i, []).append(bit)
        for slice_i, group in sorted(groups.items()):
            rows = [int(b[0]) for b in group]
            cols = [int(b[1]) for b in group]
            ts = [int(b[2]) if len(b) > 2 and b[2] else 0 for b in group]
            payload = wire.encode_import_request(
                index, frame, slice_i, rows, cols, ts if any(ts) else None
            )
            hosts = [self.base]
            if fragment_nodes is not None:
                hosts = [n.host for n in fragment_nodes(index, slice_i)]
            for host in hosts:
                client = self if host == self.base else Client(host, self.timeout)
                status, resp = client._request(
                    "POST", "/import", payload, content_type=PROTOBUF, accept=PROTOBUF
                )
                if status >= 400:
                    raise ClientError(status, resp.decode(errors="replace"))

    # -- export / backup / restore (client.go:463-676) ----------------------

    # -- streaming columnar ingest (POST .../ingest) ------------------------

    def ingest_chunk(self, index: str, frame: str, off: int, total: int,
                     crc: int, body: bytes, ccrc: Optional[int] = None,
                     probe: bool = False, deadline=None,
                     door: str = "ingest", arrow: bool = False):
        """One chunk of a streaming ingest transfer; returns
        ``(status, parsed-json)`` — 409 answers (offset gaps / resume
        hints) come back as data, not exceptions, so the streamer can
        adopt the server's ``staged`` frontier.  ``door`` selects the
        endpoint (``ingest`` = streamed set_bits, ``bulk`` = device
        build); ``arrow`` marks the chunk as an Arrow IPC stream."""
        from pilosa_tpu.ingest import ARROW_CONTENT_TYPE

        q = f"/index/{index}/frame/{frame}/{door}?off={off}&total={total}&crc={crc}"
        if ccrc is not None:
            q += f"&ccrc={ccrc}"
        if probe:
            q += "&probe=1"
        status, payload = self._request(
            "POST", q, body=body,
            content_type=(
                ARROW_CONTENT_TYPE if arrow else "application/octet-stream"
            ),
            deadline=deadline,
        )
        try:
            out = json.loads(payload) if payload else {}
        except ValueError:
            out = {}
        if status >= 400 and status != 409:
            raise ClientError(status, out.get("error", payload.decode(errors="replace")))
        return status, out

    def ingest_stream(self, index: str, frame: str, rows, cols,
                      chunk_pairs: int = 65536, deadline=None,
                      door: str = "ingest", arrow: bool = False) -> dict:
        """Stream (row, col) columns through a columnar ingest door as
        packed-uint64 (or, with ``arrow``, Arrow IPC) chunks, resuming
        at the server's staged frontier on offset gaps (a restarted
        transfer probes first).  Chunk boundaries are a pure function
        of (rows, cols, chunk_pairs), so a resumed stream re-frames
        identically."""
        import zlib as _zlib

        from pilosa_tpu.ingest import encode_packed

        if arrow:
            from pilosa_tpu.bulk.egress import encode_arrow_pairs

            def _enc(r, c):
                return encode_arrow_pairs(r, c)
        else:
            _enc = encode_packed
        frames = [
            _enc(rows[i : i + chunk_pairs], cols[i : i + chunk_pairs])
            for i in range(0, len(rows), chunk_pairs)
        ] or [_enc([], [])]
        total = sum(len(f) for f in frames)
        crc = 0
        for f in frames:
            crc = _zlib.crc32(f, crc)
        _, out = self.ingest_chunk(index, frame, 0, total, crc, b"", probe=True,
                                   deadline=deadline, door=door, arrow=arrow)
        staged = int(out.get("staged", 0))
        cur = 0
        result: dict = {"staged": staged, "done": False}
        for fb in frames:
            if cur + len(fb) <= staged:
                cur += len(fb)  # already applied before a restart
                continue
            status, result = self.ingest_chunk(
                index, frame, cur, total, crc, fb,
                ccrc=_zlib.crc32(fb), deadline=deadline, door=door,
                arrow=arrow,
            )
            if status == 409:
                # Adopt the server's frontier once; anything else
                # (shrinking frontier, repeat gap) is a real error.
                srv = int(result.get("staged", -1))
                if srv <= cur:
                    raise ClientError(409, result.get("error", "ingest gap"))
                staged = srv
                if cur + len(fb) <= staged:
                    cur += len(fb)
                    continue
                raise ClientError(409, result.get("error", "ingest gap"))
            cur += len(fb)
        return result

    def bulk_stream(self, index: str, frame: str, rows, cols,
                    chunk_pairs: int = 65536, deadline=None,
                    arrow: bool = False) -> dict:
        """Stream (row, col) columns through the device-first bulk
        build door (``POST .../bulk``): same wire and resume semantics
        as :meth:`ingest_stream`, but the server packs the bits into
        fragment word planes with its engine's sort/segment/scatter
        kernel and leaves roaring materialization lazy."""
        return self.ingest_stream(
            index, frame, rows, cols, chunk_pairs=chunk_pairs,
            deadline=deadline, door="bulk", arrow=arrow,
        )

    def export_arrow(self, index: str, frame: str, view: str,
                     slice_i: int) -> bytes:
        """One fragment as an Arrow IPC stream of uint64 row/col
        columns — the exact schema the ingest doors accept."""
        status, payload = self._request(
            "GET",
            f"/export?index={index}&frame={frame}&view={view}"
            f"&slice={slice_i}&format=arrow",
        )
        if status >= 400:
            raise ClientError(status, payload.decode(errors="replace"))
        return payload

    def export_csv(self, index: str, frame: str, view: str, slice_i: int) -> str:
        status, payload = self._request(
            "GET", f"/export?index={index}&frame={frame}&view={view}&slice={slice_i}"
        )
        if status >= 400:
            raise ClientError(status, payload.decode(errors="replace"))
        return payload.decode()

    def fragment_data(self, index: str, frame: str, view: str, slice_i: int) -> Optional[bytes]:
        status, payload = self._request(
            "GET", f"/fragment/data?index={index}&frame={frame}&view={view}&slice={slice_i}"
        )
        if status == 404:
            return None
        if status >= 400:
            raise ClientError(status, payload.decode(errors="replace"))
        return payload

    def restore_fragment(self, index: str, frame: str, view: str, slice_i: int, data: bytes) -> None:
        status, payload = self._request(
            "POST",
            f"/fragment/data?index={index}&frame={frame}&view={view}&slice={slice_i}",
            data,
            content_type="application/octet-stream",
        )
        if status >= 400:
            raise ClientError(status, payload.decode(errors="replace"))

    def restore_frame(self, index: str, frame: str, host: str) -> None:
        self._json("POST", f"/index/{index}/frame/{frame}/restore?host={host}")

    # -- block sync (client.go:700-860) --------------------------------------

    def fragment_blocks(self, index: str, frame: str, view: str, slice_i: int) -> list[tuple[int, bytes]]:
        resp = self._json(
            "GET", f"/fragment/blocks?index={index}&frame={frame}&view={view}&slice={slice_i}"
        )
        return [(b["id"], bytes.fromhex(b["checksum"])) for b in resp["blocks"]]

    def block_data(self, index: str, frame: str, view: str, slice_i: int, block: int):
        status, payload = self._request(
            "GET",
            f"/fragment/block/data?index={index}&frame={frame}&view={view}&slice={slice_i}&block={block}",
            accept=PROTOBUF,
        )
        if status >= 400:
            raise ClientError(status, payload.decode(errors="replace"))
        rows, cols = wire.decode_block_data_response(payload)
        return np.array(rows, dtype=np.uint64), np.array(cols, dtype=np.uint64)

    def post_block_diff(
        self,
        index: str,
        frame: str,
        view: str,
        slice_i: int,
        set_bits: tuple[list[int], list[int]],
        clear_bits: tuple[list[int], list[int]],
    ) -> None:
        payload = wire.encode_block_diff(set_bits[0], set_bits[1], clear_bits[0], clear_bits[1])
        status, resp = self._request(
            "POST",
            f"/fragment/block/diff?index={index}&frame={frame}&view={view}&slice={slice_i}",
            payload,
            content_type=PROTOBUF,
        )
        if status >= 400:
            raise ClientError(status, resp.decode(errors="replace"))

    def column_attr_diff(self, index: str, blocks: list[tuple[int, bytes]]) -> dict[int, dict]:
        resp = self._json(
            "POST",
            f"/index/{index}/attr/diff",
            {"blocks": [{"id": b, "checksum": c.hex()} for b, c in blocks]},
        )
        return {int(k): v for k, v in resp["attrs"].items()}

    def row_attr_diff(self, index: str, frame: str, blocks: list[tuple[int, bytes]]) -> dict[int, dict]:
        resp = self._json(
            "POST",
            f"/index/{index}/frame/{frame}/attr/diff",
            {"blocks": [{"id": b, "checksum": c.hex()} for b, c in blocks]},
        )
        return {int(k): v for k, v in resp["attrs"].items()}


def _result_from_wire(r: dict, expect: str = ""):
    """Decode one wire QueryResult into executor-level result types."""
    if expect == "Count":
        return int(r.get("n", 0))
    if expect == "TopN":
        return [Pair(id=p["id"], count=p["count"]) for p in r.get("pairs", [])]
    if expect in ("SetBit", "ClearBit"):
        return bool(r.get("changed", False))
    if expect in ("SetRowAttrs", "SetColumnAttrs", "SetProfileAttrs"):
        return None
    if expect in ("Bitmap", "Intersect", "Union", "Difference", "Xor", "Range") and "bitmap" not in r:
        return QueryBitmap({}, {})
    if "bitmap" in r:
        bits = np.array(r["bitmap"]["bits"], dtype=np.uint64)
        segments: dict[int, np.ndarray] = {}
        if len(bits):
            slices = bits // np.uint64(SLICE_WIDTH)
            for s in np.unique(slices):
                local = bits[slices == s] % np.uint64(SLICE_WIDTH)
                segments[int(s)] = pack_positions(local)
        return QueryBitmap(segments, r["bitmap"].get("attrs") or {})
    if "pairs" in r:
        return [Pair(id=p["id"], count=p["count"]) for p in r["pairs"]]
    if "changed" in r:
        return r["changed"]
    if "n" in r:
        return r["n"]
    return None


def bits_group_by_slice(bits: Sequence[tuple]) -> dict[int, list[tuple]]:
    """client.go:1027-1043 Bits.GroupBySlice."""
    groups: dict[int, list[tuple]] = {}
    for bit in bits:
        groups.setdefault(int(bit[1]) // SLICE_WIDTH, []).append(bit)
    return groups
