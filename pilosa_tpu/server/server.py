"""Server composition: holder + executor + handler + cluster + loops.

Reference analog: server.go (wiring + lifecycle server.go:42-158) and
server/server.go (cluster-type selection).  Background loops:

- anti-entropy every ``anti_entropy_interval`` (default 10 min,
  server.go:186-218) via HolderSyncer,
- max-slice polling of peers every ``polling_interval`` (default 60 s,
  server.go:221-256) so reads span slices created elsewhere,
- rank-cache flush every 60 s (holder.go:324-358).

Broadcast receive (server.go:259-304): schema mutations arriving from
peers are applied to the local holder.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from pilosa_tpu import broadcast as bc
from pilosa_tpu.cluster import Cluster, Node
from pilosa_tpu.config import (
    CLUSTER_TYPE_GOSSIP,
    CLUSTER_TYPE_HTTP,
    CLUSTER_TYPE_STATIC,
    Config,
)
from pilosa_tpu.core.frame import FrameOptions
from pilosa_tpu.core.holder import CACHE_FLUSH_INTERVAL, Holder
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.executor import Executor
import logging

from pilosa_tpu.pilosa import PilosaError
from pilosa_tpu.server.client import Client
from pilosa_tpu.server.handler import Handler, serve
from pilosa_tpu.syncer import HolderSyncer

_logger = logging.getLogger("pilosa_tpu")


class Server:
    def __init__(self, config: Optional[Config] = None, stats=None):
        from pilosa_tpu.stats import new_stats_client

        self.config = config or Config()
        if stats is None:
            stats = new_stats_client(self.config.stats)
        self.stats = stats
        self.host = self.config.host
        self.data_dir = os.path.expanduser(self.config.data_dir)

        # [cache] ranking-debounce-s threads through holder construction
        # (Holder -> Index -> Frame -> View -> Fragment), never a module
        # global — two servers in one process keep independent settings.
        self.holder = Holder(
            self.data_dir,
            stats=stats,
            ranking_debounce_s=self.config.ranking_debounce_s,
        )
        self.cluster = self._build_cluster()
        # Peer clients inherit the configured retry budget ([client]
        # retry-budget) and count their retries into this server's stats.
        self.client_factory = lambda host: Client(
            host, retry_budget=self.config.client_retry_budget, stats=stats
        )
        # Multi-tenant isolation ([tenancy]): the shared resolution seam
        # + fair-share/quota/pacer state handed to the admission doors,
        # the qcache, and the handler.  None (the default) keeps every
        # seam on its pre-tenancy path byte-identically.
        from pilosa_tpu import tenancy as tenancy_mod

        self.tenancy = tenancy_mod.from_config(self.config, stats=stats)
        # Generation-keyed query result cache ([qcache]): sits in front
        # of the executor's read paths; None = disabled.
        from pilosa_tpu.qcache import QueryCache

        self.qcache = (
            QueryCache(
                max_bytes=self.config.qcache_max_bytes,
                min_cost_ms=self.config.qcache_min_cost_ms,
                stats=stats,
                tenancy=self.tenancy,
            )
            if self.config.qcache_enabled
            else None
        )
        # Device-side cost attribution + per-fingerprint cost ledger
        # (costs.py): the meter instruments the executor's engine
        # dispatch seams, the ledger folds finished traces and serves
        # /debug/costs.  PILOSA_TPU_COSTS=0 disables both (the bench
        # overhead gate's A/B lever).
        from pilosa_tpu import costs as costs_mod

        self.costs = (
            costs_mod.CostLedger(stats=stats)
            if costs_mod.enabled_from_env()
            else None
        )
        # Cost-based adaptive planner ([planner]): turns the ledger from
        # telemetry into control flow — per-fingerprint lane selection
        # (consulted by the handler front door, applied by the executor),
        # ledger-derived budgets, and optional background pre-arming.
        # All three require the ledger; PILOSA_TPU_COSTS=0 or [planner]
        # enabled=false keeps the pre-planner static behavior exactly.
        self.planner = None
        self.budgets = None
        self.prearmer = None
        if self.costs is not None and self.config.planner_enabled:
            from pilosa_tpu import planner as planner_mod

            self.planner = planner_mod.Planner(
                self.costs,
                min_samples=self.config.planner_min_samples,
                hysteresis=self.config.planner_hysteresis,
                explore_every=self.config.planner_explore_every,
                pin=self.config.planner_pin_lane,
                stats=stats,
            )
            if self.config.planner_adaptive_budgets:
                self.budgets = planner_mod.AdaptiveBudgets(
                    self.costs,
                    qcache_min_cost_ms=self.config.qcache_min_cost_ms,
                    resync_chunk_bytes=self.config.replica_resync_chunk_bytes,
                    stats=stats,
                )
                if self.qcache is not None:
                    self.qcache.budgets = self.budgets
            if self.config.planner_prearm_budget_ms > 0:
                self.prearmer = planner_mod.PreArmer(
                    budget_ms=self.config.planner_prearm_budget_ms,
                    stats=stats,
                )
        self.executor = Executor(
            self.holder,
            engine=self.config.engine,
            cluster=self.cluster if len(self.cluster.nodes) > 1 else None,
            client_factory=self.client_factory,
            host=self.host,
            max_writes_per_request=self.config.max_writes_per_request,
            serve_state_cache=self.config.serve_state_cache,
            repair_rows_max=self.config.repair_rows_max,
            gram_rows_max=self.config.gram_rows_max,
            no_gram=self.config.no_gram,
            stream_bytes=self.config.stream_bytes,
            slice_chunk=self.config.slice_chunk,
            matrix_cache_entries=self.config.matrix_cache_entries,
            matrix_rows_max=self.config.matrix_rows_max,
            qcache=self.qcache,
            # Server ingest routes singleton SetBits through the
            # group-commit queue (concurrent clients batch into one
            # fragment pass + WAL append); opt out via env for A/B runs.
            write_queue=os.environ.get("PILOSA_TPU_WRITE_QUEUE", "1").lower()
            not in ("0", "false", "no"),
            stats=stats if self.costs is not None else None,
        )
        # The executor APPLIES plans (ExecOptions.plan) and folds
        # outcomes back; it never consults — see executor.__init__.
        self.executor.planner = self.planner
        self.executor.prearmer = self.prearmer
        self.broadcaster, self.receiver = self._build_broadcast()
        # Request-scoped span tracer ([trace] sample-rate / slow-ms /
        # ring).  Always constructed: the zero-rate default costs one
        # header lookup per request and keeps the X-Pilosa-Trace force
        # override (and the slow-query log, when slow-ms is set) live.
        from pilosa_tpu import trace as trace_mod

        self.tracer = trace_mod.from_config(self.config, stats=stats,
                                            costs=self.costs)
        from pilosa_tpu.qos import CLASS_ADMIN, CLASS_READ, CLASS_WRITE, AdmissionController

        self.admission = AdmissionController(
            depths={
                CLASS_READ: self.config.qos_read_depth,
                CLASS_WRITE: self.config.qos_write_depth,
                CLASS_ADMIN: self.config.qos_admin_depth,
            },
            queue_wait_ms=self.config.qos_queue_wait_ms,
            retry_after_ms=self.config.qos_retry_after_ms,
            stats=stats,
            tenancy=self.tenancy,
        )
        # Replica durability: a group-tagged server persists its
        # last-applied router write sequence next to the data, so a
        # RESTARTED group reports where it left off and the router
        # replays exactly the missed WAL suffix (replica/catchup.py).
        from pilosa_tpu.replica.catchup import AppliedSeq

        self.applied_seq = (
            AppliedSeq(os.path.join(self.data_dir, "applied_seq"))
            if self.config.replica_group
            else None
        )
        self.handler = Handler(
            self.holder,
            self.executor,
            cluster=self.cluster,
            host=self.host,
            broadcaster=bc.SchemaBroadcaster(self.broadcaster),
            stats=stats,
            client_factory=self.client_factory,
            admission=self.admission,
            default_deadline_ms=self.config.default_deadline_ms,
            tracer=self.tracer,
            # [replica] group: this server's serving-group identity
            # behind the replica router (X-Pilosa-Group on responses).
            group=self.config.replica_group,
            applied_seq=self.applied_seq,
            # [ingest] chunk-bytes: the streaming bulk-ingest door's
            # per-chunk ceiling.
            ingest_chunk_bytes=self.config.ingest_chunk_bytes,
            costs=self.costs,
            # [planner]: the front-door consultation point (plan_for per
            # query request) and the /debug/planner payload.
            planner=self.planner,
            # [bulk]: device bulk build door (POST .../bulk) commit
            # batching + lazy-materialization drain budget.
            bulk_batch_slices=self.config.bulk_batch_slices,
            bulk_materialize_budget_ms=self.config.bulk_materialize_budget_ms,
            # [tenancy]: resolution + fair-share enforcement state (None
            # = isolation off).
            tenancy=self.tenancy,
        )
        self.syncer = HolderSyncer(
            self.holder, self.cluster, self.host, self.client_factory, stats=stats
        )

        self._httpd = None
        self._closing = threading.Event()
        self._threads: list[threading.Thread] = []
        # Distinct (kind, name) items already warned about during status
        # merges — a steady-state bad peer item logs once, not per sync.
        self._merge_warned: set[tuple] = set()

    # -- wiring ----------------------------------------------------------

    def _build_cluster(self) -> Cluster:
        hosts = self.config.cluster.hosts or [self.config.host]
        internal = self.config.cluster.internal_hosts
        nodes = [
            Node(host=h, internal_host=internal[i] if i < len(internal) else "")
            for i, h in enumerate(hosts)
        ]
        return Cluster(nodes=nodes, replica_n=self.config.cluster.replica_n)

    def _build_broadcast(self):
        ctype = self.config.cluster.type
        # Gossip membership is dynamic — a single configured host still
        # gossips; the other types need a static peer list to matter.
        if ctype == CLUSTER_TYPE_STATIC or (
            ctype != CLUSTER_TYPE_GOSSIP and len(self.cluster.nodes) <= 1
        ):
            return bc.NopBroadcaster(), None
        if ctype == CLUSTER_TYPE_HTTP:
            me = self.cluster.node_by_host(self.host)
            my_internal = me.internal_host if me else ""
            internal_hosts = [n.internal_host or n.host for n in self.cluster.nodes]
            broadcaster = bc.HTTPBroadcaster(internal_hosts, self_host=my_internal, stats=self.stats)
            port = 0
            if my_internal and ":" in my_internal:
                port = int(my_internal.rsplit(":", 1)[1])
            receiver = bc.HTTPBroadcastReceiver(port)
            return broadcaster, receiver
        if ctype == CLUSTER_TYPE_GOSSIP:
            # SWIM gossip: UDP probe/piggyback + TCP push/pull, with this
            # server as the StatusHandler (gossip/gossip.go, server.go:310-391).
            from pilosa_tpu.gossip import GossipNodeSet

            me = self.cluster.node_by_host(self.host)
            bind = (me.internal_host if me and me.internal_host else "127.0.0.1:0")
            nodeset = GossipNodeSet(
                name=self.host,
                bind=bind,
                seed=self.config.cluster.gossip_seed,
                status_handler=self,
                stats=self.stats,
            )
            return nodeset, nodeset
        raise ValueError(f"unknown cluster type: {ctype}")

    # -- lifecycle (server.go:92-158) --------------------------------------

    def open(self) -> None:
        os.makedirs(self.data_dir, exist_ok=True)
        self.holder.open()
        self.holder.on_new_fragment = self._on_new_fragment
        host, port = self._split_host(self.host)
        # workers > 1 implies SO_REUSEPORT so sibling worker processes
        # (spawned at the CLI level on GIL builds) can share the port.
        self._httpd = serve(
            self.handler, host=host, port=port,
            max_threads=self.config.server_max_threads,
            reuse_port=self.config.server_workers > 1,
            retry_after_s=self.config.qos_retry_after_ms / 1000.0,
        )
        actual_port = self._httpd.server_address[1]
        if port == 0:
            self.host = f"{host}:{actual_port}"
            self.handler.host = self.host
            self.executor.host = self.host
            self.syncer.host = self.host
            if self.cluster.nodes and self.cluster.nodes[0].host == self.config.host:
                self.cluster.nodes[0].host = self.host
        if self.receiver is not None:
            if hasattr(self.receiver, "name"):
                # Gossip members are named by the resolved API host — an
                # ephemeral ":0" config port must not leak into the name.
                self.receiver.name = self.host
            self.receiver.start(self.receive_message)
            if hasattr(self.receiver, "open"):
                self.receiver.open()  # gossip: bind sockets + join seed
        self._start_loop(self._monitor_anti_entropy, self.config.anti_entropy_interval)
        self._start_loop(self._monitor_max_slices, self.config.cluster.polling_interval)
        self._start_loop(self._flush_caches, CACHE_FLUSH_INTERVAL)
        if self.prearmer is not None:
            self.prearmer.start()

    def close(self) -> None:
        self._closing.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            # Release the listening socket and stop the pool workers
            # (a REUSEPORT sibling must not inherit a half-dead port).
            self._httpd.server_close()
            self._httpd = None
        if self.receiver is not None:
            self.receiver.close()
        if self.prearmer is not None:
            self.prearmer.close()
        self.holder.close()

    @staticmethod
    def _split_host(host: str) -> tuple[str, int]:
        host = host.replace("http://", "")
        if ":" in host:
            name, port = host.rsplit(":", 1)
            return name or "localhost", int(port)
        return host, 10101

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    def _log_merge_skip(self, key: tuple, msg: str) -> None:
        """Warn once per distinct (item, error) — steady-state bad peers
        don't spam every sync, but a NEW failure mode for the same item
        still surfaces."""
        if key in self._merge_warned:
            return
        if len(self._merge_warned) > 1024:
            self._merge_warned.clear()
        self._merge_warned.add(key)
        _logger.warning(msg)

    # -- background loops ---------------------------------------------------

    def _start_loop(self, fn, interval: float) -> None:
        def loop():
            while not self._closing.wait(interval):
                try:
                    fn()
                except Exception:
                    # A failed monitor pass (anti-entropy, max-slice poll)
                    # retries next tick; make the failures countable.
                    self.stats.count("server.monitor_errors")

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _monitor_anti_entropy(self) -> None:
        if len(self.cluster.nodes) > 1:
            self.syncer.sync_holder()

    def _monitor_max_slices(self) -> None:
        """Poll peers' /slices/max so local reads span remote slices
        (server.go:221-256)."""
        if len(self.cluster.nodes) <= 1:
            return
        for node in self.cluster.nodes:
            if node.host == self.host:
                continue
            client = self.client_factory(node.host)
            try:
                maxes = client.max_slices()
                inverse_maxes = client.max_slices(inverse=True)
            except Exception:
                self.stats.count("server.monitor_peer_errors")
                continue
            for index_name, max_slice in maxes.items():
                idx = self.holder.index(index_name)
                if idx is not None:
                    idx.set_remote_max_slice(max_slice)
            for index_name, max_slice in inverse_maxes.items():
                idx = self.holder.index(index_name)
                if idx is not None:
                    idx.set_remote_max_inverse_slice(max_slice)

    def _flush_caches(self) -> None:
        self.holder.flush_caches()

    # -- broadcast integration ----------------------------------------------

    def _on_new_fragment(self, index: str, frame: str, view: str, slice_i: int) -> None:
        """New max slice created locally → async CreateSliceMessage
        (view.go:219-254)."""
        from pilosa_tpu.core.view import VIEW_INVERSE

        try:
            self.broadcaster.send_async(
                bc.encode_create_slice(index, slice_i, is_inverse=(view == VIEW_INVERSE))
            )
        except Exception:
            self.stats.count("server.broadcast_errors")

    # -- StatusHandler (server.go:310-391, carried by gossip push/pull) -----

    def local_status(self) -> bytes:
        """Encode this node's schema + owned slices as internal.NodeStatus
        (server.go:310-327)."""
        from pilosa_tpu import wire

        indexes = []
        for name, idx in sorted(self.holder.indexes.items()):
            max_slice = idx.max_slice()
            indexes.append({
                "name": name,
                "meta": {"columnLabel": idx.column_label, "timeQuantum": idx.time_quantum},
                "maxSlice": max_slice,
                "frames": [
                    {"name": fname, "meta": fr.schema_json()}
                    for fname, fr in sorted(idx.frames.items())
                ],
                "slices": self.cluster.owns_slices(name, max_slice, self.host),
            })
        return wire.encode_node_status(self.host, "UP", indexes)

    def handle_remote_status(self, buf: bytes) -> None:
        """Merge a peer's NodeStatus: create missing indexes/frames, track
        remote max slices (server.go:355-391)."""
        from pilosa_tpu import wire

        ns = wire.decode_node_status(buf)
        node = self.cluster.node_by_host(ns.get("host", ""))
        if node is not None and ns.get("state"):
            node.state = ns["state"]
        for idx_status in ns.get("indexes", []):
            # Per-item isolation: one peer-advertised index/frame with
            # invalid options (e.g. persisted by an older node) must not
            # abort the REST of the merge — later entries and remote
            # max-slice tracking still apply.
            try:
                name = idx_status["name"]
                meta = idx_status.get("meta", {}) or {}
                idx = self.holder.create_index_if_not_exists(
                    name,
                    IndexOptions(
                        column_label=meta.get("columnLabel", ""),
                        time_quantum=meta.get("timeQuantum", ""),
                    ),
                )
            except (PilosaError, KeyError, TypeError, AttributeError) as e:
                # Invalid options OR a structurally-malformed item from a
                # different-version peer: skip it, keep merging the rest.
                self._log_merge_skip(
                    ("index", str(idx_status.get("name")), str(e)),
                    f"status merge: skipping index {idx_status.get('name')!r}: {e}",
                )
                continue
            for fr in idx_status.get("frames", []):
                try:
                    fmeta = fr.get("meta", {}) or {}
                    idx.create_frame_if_not_exists(
                        fr["name"],
                        FrameOptions(
                            row_label=fmeta.get("rowLabel", ""),
                            inverse_enabled=fmeta.get("inverseEnabled", False),
                            cache_type=fmeta.get("cacheType", ""),
                            cache_size=fmeta.get("cacheSize", 0),
                            time_quantum=fmeta.get("timeQuantum", ""),
                        ),
                    )
                except (PilosaError, KeyError, TypeError, AttributeError) as e:
                    self._log_merge_skip(
                        ("frame", name, str(fr.get("name") if hasattr(fr, "get") else fr), str(e)),
                        f"status merge: skipping frame {name}/{fr!r}: {e}",
                    )
            if idx_status.get("maxSlice", 0) > idx.max_slice():
                idx.set_remote_max_slice(idx_status["maxSlice"])

    def receive_message(self, data: bytes) -> None:
        """Apply a peer's schema mutation (server.go:259-304)."""
        typ, msg = bc.decode_message(data)
        if typ == bc.MESSAGE_TYPE_CREATE_SLICE:
            idx = self.holder.index(msg["index"])
            if idx is not None:
                if msg.get("isInverse"):
                    idx.set_remote_max_inverse_slice(msg["slice"])
                else:
                    idx.set_remote_max_slice(msg["slice"])
        elif typ == bc.MESSAGE_TYPE_CREATE_INDEX:
            meta = msg.get("meta", {})
            self.holder.create_index_if_not_exists(
                msg["index"],
                IndexOptions(
                    column_label=meta.get("columnLabel", ""),
                    time_quantum=meta.get("timeQuantum", ""),
                ),
            )
        elif typ == bc.MESSAGE_TYPE_DELETE_INDEX:
            try:
                self.holder.delete_index(msg["index"])
            except Exception:
                # Remote delete for an index this node never created:
                # already converged, but keep the count honest.
                self.stats.count("server.receive_message_errors")
        elif typ == bc.MESSAGE_TYPE_CREATE_FRAME:
            idx = self.holder.index(msg["index"])
            if idx is not None:
                meta = msg.get("meta", {})
                idx.create_frame_if_not_exists(
                    msg["frame"],
                    FrameOptions(
                        row_label=meta.get("rowLabel", ""),
                        inverse_enabled=meta.get("inverseEnabled", False),
                        cache_type=meta.get("cacheType", ""),
                        cache_size=meta.get("cacheSize", 0),
                        time_quantum=meta.get("timeQuantum", ""),
                    ),
                )
        elif typ == bc.MESSAGE_TYPE_DELETE_FRAME:
            idx = self.holder.index(msg["index"])
            if idx is not None:
                try:
                    idx.delete_frame(msg["frame"])
                except Exception:
                    self.stats.count("server.receive_message_errors")
