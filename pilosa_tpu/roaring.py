"""Roaring bitmap engine, numpy-native.

Host-side compressed bitmap used at the storage/serialization boundary
(snapshot files, WAL, wire format).  On device everything is dense packed
uint32 (see pilosa_tpu.ops); this module is what feeds it.

Reference analog: roaring/roaring.go (1856 LoC Go).  Semantics match —
64-bit value space split into 2^16-bit containers keyed by ``value >> 16``,
each container either a sorted array (≤ 4096 values) or a dense bitmap
(1024 × u64 words) — but the implementation is vectorized numpy rather than
a translation: container kernels are numpy set ops / bitwise ops, batch
adds group by key with one sort, and dense-row extraction emits the packed
uint32 arrays the TPU kernels consume.

Serialization is byte-compatible with the reference file format
(roaring.go:475-533 WriteTo / 536-614 UnmarshalBinary):

    cookie u32le = 12346 | containerCount u32le
    per container: key u64le, (n-1) u32le          (12-byte headers)
    per container: absolute file offset u32le
    payloads: array = n × u32le, bitmap = 1024 × u64le
    trailing op log: records of [typ u8 | value u64le | fnv1a32 u32le]
                     (checksum over the first 9 bytes; roaring.go:1586-1623)
"""

from __future__ import annotations

import io
import struct
import sys
from typing import Iterable, Iterator, Optional

import numpy as np

from pilosa_tpu import native

COOKIE = 12346
HEADER_SIZE = 8
ARRAY_MAX_SIZE = 4096
BITMAP_N = (1 << 16) // 64  # 1024 u64 words per container
CONTAINER_BITS = 1 << 16
OP_SIZE = 13

OP_ADD = 0
OP_REMOVE = 1

# Snapshot payload chunk size: one write syscall per ~8 MB of payloads.
_SNAP_CHUNK = 8 << 20


def _snap_release(handle: int) -> None:
    """GC finalizer for a Bitmap's native snapshot mirror (safe at
    interpreter shutdown: the lib may already be unloaded)."""
    try:
        lib = native.load()
        if lib is not None:
            lib.pn_snap_free(handle)
    # analysis-ok: exception-hygiene: finalizer during interpreter shutdown; nothing to report to
    except Exception:
        pass

# Byte-popcount lookup table; np_count(words) = LUT[words.view(u8)].sum().
_POPCNT8 = np.array([bin(i).count("1") for i in range(256)], dtype=np.uint32)


_NATIVE_LE = sys.byteorder == "little"


def _popcount_words(words: np.ndarray) -> int:
    return int(_POPCNT8[words.view(np.uint8)].sum())


def fnv1a32(data: bytes) -> int:
    """FNV-1a 32-bit hash (op-log checksums; hash/fnv analog)."""
    h = 2166136261
    for b in data:
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h


def highbits(v: int) -> int:
    return v >> 16


def lowbits(v: int) -> int:
    return v & 0xFFFF


class Container:
    """One 2^16-bit container: sorted uint32 array or dense u64 bitmap.

    ``array`` holds sorted unique lowbits values as uint32 (the file format
    stores them as u32le).  ``bitmap`` is uint64[1024].  Exactly one is
    non-None.  Conversion threshold matches the reference: arrays hold at
    most ARRAY_MAX_SIZE=4096 values (roaring.go:833, 951-953).
    """

    __slots__ = ("array", "bitmap", "_n", "_ser", "_buf", "_buf_addr")

    def __init__(self, array: Optional[np.ndarray] = None, bitmap: Optional[np.ndarray] = None):
        if array is None and bitmap is None:
            array = np.empty(0, dtype=np.uint32)
        self.array = array
        self.bitmap = bitmap
        # Cached bitmap-container cardinality (the reference stores n as a
        # field, roaring.go:42); add/remove adjust it so snapshots and
        # counts skip a popcount per container.  None = unknown.
        self._n: Optional[int] = None
        # Cached (n, payload bytes) for serialization: snapshots only
        # re-encode containers that changed since the last one (the
        # per-container-dirty incremental snapshot; cleared on mutation).
        self._ser: Optional[tuple[int, bytes]] = None
        # Capacity-slack backing buffer for the native in-place insert:
        # when set, ``array`` is ``_buf[:n]`` and single adds memmove
        # inside the buffer (no per-op allocation).  Any bulk mutation or
        # representation change drops it (array becomes standalone again).
        # _buf_addr caches buf.ctypes.data: the .ctypes property
        # materializes a wrapper object per access (~2us on the hot path).
        self._buf: Optional[np.ndarray] = None
        self._buf_addr = 0

    # -- constructors -------------------------------------------------

    @classmethod
    def from_values(cls, values: np.ndarray) -> "Container":
        """Build from sorted unique lowbits values, picking representation."""
        values = np.asarray(values, dtype=np.uint32)
        if len(values) > ARRAY_MAX_SIZE:
            return cls(bitmap=_values_to_bitmap(values))
        return cls(array=values)

    # -- basics -------------------------------------------------------

    @property
    def is_array(self) -> bool:
        return self.array is not None

    @property
    def n(self) -> int:
        if self.array is not None:
            return len(self.array)
        if self._n is None:
            self._n = _popcount_words(self.bitmap)
        return self._n

    def values(self) -> np.ndarray:
        """Sorted lowbits values as uint32.

        The returned array is safe to retain across later mutations: when
        the container is backed by the capacity-slack insert buffer (whose
        contents single adds memmove in place), it is detached here —
        published as a standalone array once — so no caller ever holds a
        live view of mutating storage.  The next native add re-creates the
        slack buffer.
        """
        if self.array is not None:
            if self._buf is not None:
                self.array = self.array.copy()
                self._buf = None
            return self.array
        return _bitmap_to_values(self.bitmap)

    def contains(self, v: int) -> bool:
        if self.array is not None:
            i = np.searchsorted(self.array, v)
            return i < len(self.array) and self.array[i] == v
        return bool((int(self.bitmap[v >> 6]) >> (v & 63)) & 1)

    def contains_many(self, lows: np.ndarray) -> np.ndarray:
        """Vectorized membership mask for uint32 lowbits values."""
        lows = np.asarray(lows, dtype=np.uint32)
        if self.array is not None:
            if len(self.array) == 0:
                return np.zeros(len(lows), dtype=bool)
            i = np.searchsorted(self.array, lows)
            mask = i < len(self.array)
            return mask & (self.array[np.minimum(i, len(self.array) - 1)] == lows)
        words = self.bitmap[(lows >> np.uint32(6)).astype(np.int64)]
        return ((words >> (lows & np.uint32(63)).astype(np.uint64)) & np.uint64(1)).astype(bool)

    def _writable_bitmap(self) -> np.ndarray:
        """Copy-on-write gate for in-place bitmap-container mutation.

        mmap-attached containers (zero-copy snapshot views,
        Bitmap.from_bytes(..., zero_copy=True)) hold READ-ONLY views into
        the mapped file; the first mutation promotes the container to a
        private heap copy — the reference's equivalent is the op log
        keeping mutations out of the mmap entirely (roaring.go:84-103 adds
        go to the WAL; the mmap stays immutable until snapshot)."""
        bm = self.bitmap
        if not bm.flags.writeable:
            bm = self.bitmap = bm.copy()
        return bm

    def _ensure_slack(self, n: int) -> np.ndarray:
        """The capacity-slack insert buffer, (re)built so capacity > n.

        Invariant shared by every native insert path: ``array`` is
        ``_buf[:n]`` and ``_buf_addr`` caches the buffer's base address.
        """
        buf = self._buf
        if buf is None or n >= len(buf):
            buf = np.empty(max(8, 2 * n), dtype=np.uint32)
            buf[:n] = self.array
            self._buf = buf
            self._buf_addr = buf.ctypes.data
        return buf

    def add(self, v: int) -> bool:
        """Insert lowbits value; True if it was newly added."""
        arr = self.array
        if arr is not None:
            n = len(arr)
            if n < ARRAY_MAX_SIZE:
                lib = native.load()
                if lib is not None:
                    # Native in-place insert over a capacity-slack buffer:
                    # one C call does the binary search, duplicate check,
                    # and memmove — no per-op numpy dispatch or allocation.
                    buf = self._ensure_slack(n)
                    newn = lib.pn_array_insert_u32(self._buf_addr, n, v)
                    if newn < 0:
                        return False
                    self._ser = None
                    self.array = buf[:newn]
                    return True
            # Direct ndarray method: the np.searchsorted module wrapper pays
            # ~3µs of dispatch machinery per call on this hot path.
            i = int(arr.searchsorted(v))
            if i < len(arr) and arr[i] == v:
                return False
            self._ser = None
            if len(arr) >= ARRAY_MAX_SIZE:
                self._buf = None
                self.bitmap = _values_to_bitmap(arr)
                self._n = len(arr) + 1
                self.array = None
                self.bitmap[v >> 6] |= np.uint64(1 << (v & 63))
                return True
            # np.insert pays axis-normalization machinery per call; a plain
            # split copy is ~3x faster on the SetBit hot path.
            new = np.empty(len(arr) + 1, dtype=np.uint32)
            new[:i] = arr[:i]
            new[i] = v
            new[i + 1:] = arr[i:]
            self._buf = None
            self.array = new
            return True
        w, b = v >> 6, v & 63
        if (int(self.bitmap[w]) >> b) & 1:
            return False
        self._ser = None
        self._writable_bitmap()[w] |= np.uint64(1 << b)
        if self._n is not None:
            self._n += 1
        return True

    def remove(self, v: int) -> bool:
        if self.array is not None:
            i = int(self.array.searchsorted(v))
            if i >= len(self.array) or self.array[i] != v:
                return False
            self._ser = None
            self._buf = None
            self.array = np.delete(self.array, i)
            return True
        w, b = v >> 6, v & 63
        if not (int(self.bitmap[w]) >> b) & 1:
            return False
        self._ser = None
        self._writable_bitmap()[w] &= np.uint64(~(1 << b) & 0xFFFFFFFFFFFFFFFF)
        if self._n is not None:
            self._n -= 1
        # Convert back to array when small enough (roaring.go remove path).
        if self.n <= ARRAY_MAX_SIZE:
            self._buf = None
            self.array = _bitmap_to_values(self.bitmap)
            self.bitmap = None
            self._n = None  # array form owns the count now
        return True

    def add_many(self, values: np.ndarray) -> int:
        """Bulk insert of sorted-or-not lowbits values; returns newly-added count."""
        values = np.asarray(values, dtype=np.uint32)
        if len(values) == 0:
            return 0
        self._ser = None
        self._buf = None
        before = self.n
        if self.bitmap is not None:
            # Dense stays dense: OR the bits in directly, O(len + 1024)
            # instead of a full unpack + union sort.
            np.bitwise_or.at(
                self._writable_bitmap(),
                (values >> np.uint32(6)).astype(np.int64),
                np.uint64(1) << (values & np.uint32(63)).astype(np.uint64),
            )
            self._n = None  # bulk OR: recount (and re-cache) below
            return self.n - before
        merged = np.union1d(self.array, values)
        if len(merged) > ARRAY_MAX_SIZE:
            self.bitmap = _values_to_bitmap(merged)
            self._n = len(merged)
            self.array = None
        else:
            self.array = merged.astype(np.uint32)
            self.bitmap = None
        return len(merged) - before

    # -- range --------------------------------------------------------

    def count_range(self, start: int, end: int) -> int:
        """Count values in [start, end) within this container's lowbits space."""
        if self.array is not None:
            return int(np.searchsorted(self.array, end) - np.searchsorted(self.array, start))
        vals = _bitmap_to_values(self.bitmap)
        return int(np.searchsorted(vals, end) - np.searchsorted(vals, start))

    # -- serialization ------------------------------------------------

    def payload(self) -> bytes:
        if self.array is not None:
            if _NATIVE_LE:
                return self.array.tobytes()
            return self.array.astype("<u4").tobytes()
        if _NATIVE_LE:
            return self.bitmap.tobytes()
        return self.bitmap.astype("<u8").tobytes()

    def payload_size(self) -> int:
        if self.array is not None:
            return 4 * len(self.array)
        return 8 * BITMAP_N

    def ser(self) -> tuple[int, bytes]:
        """(n, payload bytes), cached until the next mutation — snapshots
        re-encode only the containers that changed (incremental snapshot;
        fragment.go rewrites every container each time)."""
        s = self._ser
        if s is None:
            s = (self.n, self.payload())
            if self.array is not None and len(self.array) <= 512:
                # Only small array containers cache their payload: the win
                # is the per-container Python overhead on snapshot (small
                # containers dominate sparse fragments), while pinning
                # multi-KB copies (dense 8 KB, near-full arrays 16 KB)
                # would meaningfully grow host memory on large fragments.
                self._ser = s
        return s

    def check(self) -> None:
        if self.array is not None:
            if len(self.array) > ARRAY_MAX_SIZE:
                raise ValueError("array container too large")
            if len(self.array) > 1 and not (np.diff(self.array.astype(np.int64)) > 0).all():
                raise ValueError("array container not sorted/unique")
            if len(self.array) and int(self.array[-1]) >= CONTAINER_BITS:
                raise ValueError("array value out of range")


def _values_to_bitmap(values: np.ndarray) -> np.ndarray:
    bm = np.zeros(BITMAP_N, dtype=np.uint64)
    v = values.astype(np.uint64)
    np.bitwise_or.at(bm, (v >> np.uint64(6)).astype(np.int64), np.uint64(1) << (v & np.uint64(63)))
    return bm


def _bitmap_to_values(bitmap: np.ndarray) -> np.ndarray:
    bits = np.unpackbits(bitmap.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint32)


class Bitmap:
    """Sparse 64-bit-keyed roaring bitmap (reference roaring.go:42 Bitmap).

    ``containers`` maps container key (value >> 16) -> Container.  A dict is
    the Python-native replacement for the reference's parallel sorted
    keys/containers slices; sorted key order is materialized on demand
    (iteration/serialization) and set ops intersect key sets directly.

    ``op_writer`` is the WAL hook (roaring.go:51 OpWriter): when set, every
    successful add/remove appends a checksummed 13-byte op record.
    """

    def __init__(self, values: Optional[Iterable[int]] = None):
        self.containers: dict[int, Container] = {}
        self._op_writer = None  # file-like; WAL hook
        # Raw fd of the WAL writer for the fused native add (insert + WAL
        # record + write(2) in one C call): >= 0 usable, -1 unresolved,
        # -2 writer has no fileno (BytesIO tests — python write path).
        self._op_fd = -1
        self.op_n = 0
        # C++ incremental-snapshot mirror (see write_to): handle into the
        # native encoder + the container keys mutated since the last sync.
        # None until the first native write_to; every Bitmap mutation
        # method records dirty keys once tracking is live.
        self._snap_handle = None
        self._snap_dirty: "Optional[set[int]]" = None
        if values is not None:
            self.add_many(np.fromiter(values, dtype=np.uint64))

    @property
    def op_writer(self):
        return self._op_writer

    @op_writer.setter
    def op_writer(self, w) -> None:
        self._op_writer = w
        self._op_fd = -1  # re-resolve on next fused add

    def _wal_fd(self) -> int:
        """fd of the WAL writer, or -2 when the fused C write(2) path may
        not use it.  Only UNBUFFERED raw writers qualify: a buffered
        writer's fileno() is real, but bypassing its userspace buffer
        would let a fused ADD hit disk ahead of an unflushed earlier
        record — out-of-order replay after a crash."""
        fd = self._op_fd
        if fd == -1:
            w = self._op_writer
            if isinstance(w, io.RawIOBase):
                try:
                    fd = w.fileno()
                except (OSError, ValueError):
                    fd = -2
            else:
                fd = -2
            self._op_fd = fd
        return fd

    # -- mutation -----------------------------------------------------

    def add(self, v: int) -> bool:
        v = int(v)
        # Fused native lane (the reference's compiled SetBit chain,
        # fragment.go:371-459): container search + duplicate check +
        # memmove insert + WAL record + write(2) in ONE ctypes call.
        # Declines to the general path on any structural case: new or
        # bitmap container, no capacity slack, array at the conversion
        # threshold, or a WAL writer without a real fd.
        key = v >> 16
        c = self.containers.get(key)
        if c is None or (c.array is not None and len(c.array) < ARRAY_MAX_SIZE):
            lib = native.load()
            if lib is not None:
                if self._op_writer is None:
                    fd = -1
                else:
                    fd = self._wal_fd()
                if fd != -2:
                    if c is None:  # first touch: container + slack buffer
                        c = Container()
                        self.containers[key] = c
                        n = 0
                    else:
                        n = len(c.array)
                    buf = c._ensure_slack(n)
                    r = lib.pn_array_add_logged(c._buf_addr, n, v & 0xFFFF, v, fd)
                    if r == -2:
                        return False
                    if r == -3:
                        if n == 0:  # don't leave an empty first-touch shell
                            del self.containers[key]
                        raise OSError("WAL write failed")
                    c._ser = None
                    c.array = buf[:r]
                    d = self._snap_dirty
                    if d is not None:
                        d.add(key)
                    if fd >= 0:
                        self.op_n += 1
                    return True
        changed = self._container_for(v).add(lowbits(v))
        if changed:
            d = self._snap_dirty
            if d is not None:
                d.add(highbits(v))
            self._write_op(OP_ADD, v)
        return changed

    def remove(self, v: int) -> bool:
        v = int(v)
        c = self.containers.get(highbits(v))
        if c is None:
            return False
        changed = c.remove(lowbits(v))
        if changed:
            if c.n == 0:
                del self.containers[highbits(v)]
            d = self._snap_dirty
            if d is not None:
                d.add(highbits(v))
            self._write_op(OP_REMOVE, v)
        return changed

    def add_unlogged(self, v: int) -> bool:
        """Scalar add WITHOUT the WAL — the tiny-batch ingest fast path
        (fragment.set_bits): callers apply a handful of scalar adds and
        then append ONE combined op-log record batch via log_add_ops."""
        v = int(v)
        changed = self._container_for(v).add(lowbits(v))
        if changed and self._snap_dirty is not None:
            self._snap_dirty.add(highbits(v))
        return changed

    def _bulk_add(self, values: np.ndarray) -> np.ndarray:
        """Shared bulk-add core: apply sorted-unique uint64 values and
        return the (sorted) subset that was newly added.  No WAL."""
        keys = (values >> np.uint64(16)).astype(np.int64)
        # values is sorted, so per-key groups are contiguous: one pass.
        uniq_keys, starts = np.unique(keys, return_index=True)
        groups = np.split(values, starts[1:])
        added_groups = []
        for key, group in zip(uniq_keys.tolist(), groups):
            lows = (group & np.uint64(0xFFFF)).astype(np.uint32)
            # analysis-ok: check-then-act: Bitmap is externally synchronized (Roaring-library contract): every mutating call site holds the owning fragment's _mu
            c = self.containers.get(key)
            if c is None:
                self.containers[key] = Container.from_values(lows)
                new_lows = lows
            elif len(lows) <= 8 and c.array is not None and len(c.array) + len(lows) <= ARRAY_MAX_SIZE:
                # Scattered-batch fast path: a handful of inserts into an
                # array container goes through the native in-place insert
                # (a few us total) instead of the vectorized
                # contains_many + union1d machinery (~30us of numpy
                # dispatch per container, the set_bits hot cost).
                new = [int(v) for v in lows.tolist() if c.add(int(v))]
                new_lows = np.asarray(new, dtype=np.uint32)
            else:
                new_lows = lows[~c.contains_many(lows)]
                if len(new_lows):
                    c.add_many(new_lows)
            if len(new_lows):
                if self._snap_dirty is not None:
                    self._snap_dirty.add(key)
                added_groups.append(new_lows.astype(np.uint64) | np.uint64(key << 16))
        if not added_groups:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(added_groups)

    def add_many(self, values: np.ndarray) -> int:
        """Vectorized bulk add (no WAL; callers snapshot after, like Import)."""
        return len(self.add_many_unlogged(values))

    def add_many_unlogged(self, values: np.ndarray) -> np.ndarray:
        """Apply a batch WITHOUT touching the WAL; returns the sorted
        uint64 array of newly-added values.  Callers own durability:
        either snapshot afterwards (import path) or pass the result to
        ``log_add_ops`` (small-batch path)."""
        values = np.asarray(values, dtype=np.uint64)
        if len(values) == 0:
            return values
        return self._bulk_add(np.unique(values))

    def add_many_logged(self, values: np.ndarray) -> np.ndarray:
        """Vectorized add WITH WAL: applies the batch and appends one op
        record per newly-set value (a durable bulk SetBit, unlike
        ``add_many`` which callers must follow with a snapshot).

        Returns the sorted uint64 array of values that were newly added.
        """
        added = self.add_many_unlogged(values)
        self.log_add_ops(added)
        return added

    def log_add_ops(self, added: np.ndarray) -> None:
        """Append one OP_ADD record per value to the WAL (no-op when
        detached).  For callers that apply a batch first and decide on
        durability strategy after seeing what was actually new."""
        if len(added) == 0 or self.op_writer is None:
            return
        if len(added) <= 8:
            # The native encoder costs ~40 us of ctypes marshalling per
            # call; a handful of records pack faster in pure python.
            self.op_writer.write(
                b"".join(encode_op(OP_ADD, int(v)) for v in added)
            )
            # analysis-ok: check-then-act: Bitmap is externally synchronized (Roaring-library contract): every mutating call site holds the owning fragment's _mu
            self.op_n += len(added)
            return
        types = np.zeros(len(added), dtype=np.uint8)  # OP_ADD
        self.op_writer.write(native.oplog_encode(types, added))
        # analysis-ok: check-then-act: Bitmap is externally synchronized (Roaring-library contract): every mutating call site holds the owning fragment's _mu
        self.op_n += len(added)

    def _container_for(self, v: int) -> Container:
        key = highbits(v)
        # analysis-ok: check-then-act: Bitmap is externally synchronized (Roaring-library contract): every mutating call site holds the owning fragment's _mu
        c = self.containers.get(key)
        if c is None:
            c = Container()
            self.containers[key] = c
        return c

    def _write_op(self, typ: int, value: int) -> None:
        if self.op_writer is None:
            return
        self.op_writer.write(native.op_encode1(typ, value))
        self.op_n += 1

    # -- queries ------------------------------------------------------

    def contains(self, v: int) -> bool:
        v = int(v)
        c = self.containers.get(highbits(v))
        return c is not None and c.contains(lowbits(v))

    def count(self) -> int:
        return sum(c.n for c in self.containers.values())

    def _keys_in_range(self, hk: int, he: int):
        """Container keys present in [hk, he], UNSORTED.  Iterates whichever
        side is smaller — the key range (a row spans ≤16 consecutive keys,
        the SetBit hot path) or the container dict."""
        if he - hk + 1 <= len(self.containers):
            return [k for k in range(hk, he + 1) if k in self.containers]
        return [k for k in self.containers if hk <= k <= he]

    def count_range(self, start: int, end: int) -> int:
        """Count values in [start, end)."""
        if end <= start:
            return 0
        total = 0
        hk, he = highbits(start), highbits(end - 1)
        for key in self._keys_in_range(hk, he):  # counting needs no order
            c = self.containers[key]
            lo = lowbits(start) if key == hk else 0
            hi = lowbits(end - 1) + 1 if key == he else CONTAINER_BITS
            if lo == 0 and hi == CONTAINER_BITS:
                total += c.n
            else:
                total += c.count_range(lo, hi)
        return total

    def slice_values(self, start: int, end: int) -> np.ndarray:
        """All values in [start, end) as sorted uint64 (OffsetRange core)."""
        out = []
        hk, he = highbits(start), highbits(max(end - 1, 0))
        for key in sorted(self._keys_in_range(hk, he)):
            vals = self.containers[key].values().astype(np.uint64) | np.uint64(key << 16)
            if key == hk or key == he:
                vals = vals[(vals >= start) & (vals < end)]
            out.append(vals)
        if not out:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(out)

    def offset_range(self, offset: int, start: int, end: int) -> "Bitmap":
        """New bitmap holding values in [start, end) rebased to ``offset``.

        Reference roaring.go:253-285: container keys are shifted whole —
        offset/start/end must be container-aligned multiples of 2^16.
        """
        for name, v in (("offset", offset), ("start", start), ("end", end)):
            if v & 0xFFFF:
                raise ValueError(f"{name} must be a multiple of 2^16")
        other = Bitmap()
        off_key, hi0, hi1 = highbits(offset), highbits(start), highbits(end)
        for key, c in self.containers.items():
            if hi0 <= key < hi1:
                other.containers[off_key + (key - hi0)] = Container(
                    array=None if c.array is None else c.array.copy(),
                    bitmap=None if c.bitmap is None else c.bitmap.copy(),
                )
        return other

    def sorted_keys(self) -> list[int]:
        return sorted(self.containers.keys())

    def max(self) -> int:
        """Largest value present (0 when empty; roaring.go Max analog)."""
        if not self.containers:
            return 0
        key = max(self.containers)
        vals = self.containers[key].values()
        return (key << 16) | int(vals[-1]) if len(vals) else 0

    # -- set algebra --------------------------------------------------

    def intersect(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap()
        for key in self.containers.keys() & other.containers.keys():
            c = _c_intersect(self.containers[key], other.containers[key])
            if c.n:
                out.containers[key] = c
        return out

    def union(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap()
        for key in self.containers.keys() | other.containers.keys():
            a, b = self.containers.get(key), other.containers.get(key)
            if a is None:
                out.containers[key] = _c_copy(b)
            elif b is None:
                out.containers[key] = _c_copy(a)
            else:
                out.containers[key] = _c_union(a, b)
        return out

    def difference(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap()
        for key, a in self.containers.items():
            b = other.containers.get(key)
            c = _c_copy(a) if b is None else _c_difference(a, b)
            if c.n:
                out.containers[key] = c
        return out

    def intersection_count(self, other: "Bitmap") -> int:
        """|self ∩ other| without materializing (the popcntAndSlice host path)."""
        total = 0
        for key in self.containers.keys() & other.containers.keys():
            total += _c_intersection_count(self.containers[key], other.containers[key])
        return total

    def xor(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap()
        for key in self.containers.keys() | other.containers.keys():
            a, b = self.containers.get(key), other.containers.get(key)
            if a is None:
                out.containers[key] = _c_copy(b)
            elif b is None:
                out.containers[key] = _c_copy(a)
            else:
                c = Container.from_values(np.setxor1d(a.values(), b.values()))
                if c.n:
                    out.containers[key] = c
        return out

    # -- iteration ----------------------------------------------------

    def __iter__(self) -> Iterator[int]:
        for key in self.sorted_keys():
            base = key << 16
            for v in self.containers[key].values():
                yield base | int(v)

    def to_array(self) -> np.ndarray:
        """All values as a sorted uint64 array."""
        keys = self.sorted_keys()
        if not keys:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(
            [self.containers[k].values().astype(np.uint64) | np.uint64(k << 16) for k in keys]
        )

    # -- dense bridge (device boundary) --------------------------------

    def to_dense_words(self, start: int, n_bits: int) -> np.ndarray:
        """Pack values in [start, start+n_bits) into uint32 words.

        The bridge to the TPU side: a fragment row becomes
        to_dense_words(row*SLICE_WIDTH, SLICE_WIDTH) → uint32[32768].
        Requires container-aligned start and n_bits (multiples of 2^16).
        """
        if start & 0xFFFF:
            raise ValueError("start must be container-aligned")
        if n_bits <= 0 or n_bits & 0xFFFF:
            raise ValueError("n_bits must be a positive multiple of 2^16")
        n_words = n_bits // 32
        out = np.zeros(n_words, dtype=np.uint32)
        k0, k1 = highbits(start), highbits(start + n_bits - 1)
        for key in self.containers.keys():
            if not (k0 <= key <= k1):
                continue
            c = self.containers[key]
            word_off = ((key - k0) << 16) // 32
            if c.bitmap is not None:
                out[word_off : word_off + 2048] = c.bitmap.view(np.uint32)[: 2 * BITMAP_N]
            elif len(c.array):
                v = c.array.astype(np.int64)
                np.bitwise_or.at(
                    out, word_off + (v >> 5), (np.uint32(1) << (v & 31).astype(np.uint32))
                )
        return out

    @classmethod
    def from_dense_words(cls, words: np.ndarray, start: int = 0) -> "Bitmap":
        """Inverse of to_dense_words (start container-aligned)."""
        if start & 0xFFFF:
            raise ValueError("start must be container-aligned")
        bm = cls()
        words = np.ascontiguousarray(words, dtype=np.uint32)
        bits = np.unpackbits(words.view(np.uint8), bitorder="little")
        positions = np.nonzero(bits)[0].astype(np.uint64) + np.uint64(start)
        bm.add_many(positions)
        return bm

    # -- consistency ---------------------------------------------------

    def check(self) -> None:
        """Invariant check (roaring.go:653-674 Bitmap.Check analog)."""
        for key, c in self.containers.items():
            if key < 0 or key > (1 << 48):
                raise ValueError(f"container key out of range: {key}")
            c.check()

    # -- serialization -------------------------------------------------

    def write_to(self, w) -> int:
        """Serialize in the reference's cookie-12346 format.

        With the native library, snapshots are INCREMENTAL: a C++-side
        mirror keeps every container's encoded payload, Python pushes only
        the keys dirtied since the last write_to, and the full image is
        emitted by one C call — the per-container Python loop (which
        dominated SetBit's amortized cost on sparse fragments) runs only
        over the dirty set.  Fallback: vectorized numpy header building.
        """
        lib = native.load()
        if lib is not None and _NATIVE_LE and self._snap_profitable():
            return self._write_to_native(lib, w)
        if self._snap_handle is not None:
            # Shape drifted out of the profitable regime (e.g. ingest
            # densified the containers): drop the mirror and its memory.
            _snap_release(self._snap_handle)
            self._snap_handle = None
            self._snap_dirty = None
        return self._write_to_python(w)

    def _snap_profitable(self) -> bool:
        """Whether the C++ incremental-snapshot mirror pays for itself.

        The mirror pins an encoded copy of every container in C++ heap,
        and its win is amortizing the per-container Python loop — so it
        pays exactly when containers are MANY and SMALL (sparse
        fragments, the SetBit-hot shape).  Dense shapes (few, 8 KB
        containers) keep the vectorized Python writer: the loop is short
        there and the pinned copies would roughly double resident
        memory.  Sampled, not exact: O(64) per call.
        """
        n = len(self.containers)
        if n < 512:
            return False
        import itertools

        sample = list(itertools.islice(self.containers.values(), 64))
        avg = sum(c.payload_size() for c in sample) / len(sample)
        return avg <= 256.0

    def _write_to_python(self, w) -> int:
        # One pass over sorted keys reading the _ser slot directly: for a
        # mostly-clean bitmap (the steady SetBit state) each container
        # costs one attribute read, not repeated n-property calls.
        keys: list[int] = []
        ns_list: list[int] = []
        conts: list[Container] = []
        for k in self.sorted_keys():
            c = self.containers[k]
            s = c._ser
            cn = s[0] if s is not None else c.n
            if cn > 0:
                keys.append(k)
                ns_list.append(cn)
                conts.append(c)
        n = len(keys)
        written = w.write(np.array([COOKIE, n], dtype="<u4").tobytes())
        if n:
            ns = np.asarray(ns_list, dtype=np.int64)
            meta = np.zeros(n, dtype=[("key", "<u8"), ("n1", "<u4")])
            meta["key"] = np.asarray(keys, dtype=np.uint64)
            meta["n1"] = (ns - 1).astype(np.uint32)
            written += w.write(meta.tobytes())
            sizes = np.where(ns <= ARRAY_MAX_SIZE, ns * 4, BITMAP_N * 8)
            offsets = HEADER_SIZE + n * 16 + np.concatenate(([0], np.cumsum(sizes[:-1])))
            written += w.write(offsets.astype("<u4").tobytes())
            # Payloads are produced lazily (cached for small dirty-tracked
            # arrays, fresh for dense containers) and written in ~8 MB
            # joined chunks: few syscalls, and peak extra memory stays one
            # chunk — never the whole serialized image.
            chunk: list[bytes] = []
            chunk_bytes = 0
            for c in conts:
                s = c._ser
                p = s[1] if s is not None else c.ser()[1]
                chunk.append(p)
                chunk_bytes += len(p)
                if chunk_bytes >= _SNAP_CHUNK:
                    written += w.write(b"".join(chunk))
                    chunk, chunk_bytes = [], 0
            if chunk:
                written += w.write(b"".join(chunk))
        return written

    def _write_to_native(self, lib, w) -> int:
        """Incremental snapshot emit via the C++ mirror (pn_snap_*)."""
        h = self._snap_handle
        if h is None:
            h = lib.pn_snap_new()
            self._snap_handle = h
            import weakref

            weakref.finalize(self, _snap_release, h)
            dirty = list(self.containers.keys())  # first sync: everything
        else:
            dirty = self._snap_dirty
        self._snap_dirty = set()  # tracking live from now on
        containers = self.containers
        snap_set, snap_del = lib.pn_snap_set, lib.pn_snap_del
        for k in dirty:
            c = containers.get(k)
            if c is None:
                snap_del(h, k)
                continue
            n, payload = c.ser()
            if n == 0:
                snap_del(h, k)
            else:
                snap_set(h, k, n, payload, len(payload))
        size = lib.pn_snap_image_size(h)
        buf = np.empty(size, dtype=np.uint8)
        got = lib.pn_snap_emit(h, buf.ctypes.data, size)
        if got != size:  # registry raced a free: fall back, stay correct
            self._snap_handle, self._snap_dirty = None, None
            return self._write_to_python(w)
        w.write(memoryview(buf))
        return size

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        self.write_to(buf)
        return buf.getvalue()

    @classmethod
    def _parse_snapshot(cls, data, zero_copy: bool = False) -> tuple["Bitmap", int]:
        """Strict snapshot-body decode; returns (bitmap, op-log offset).

        ``zero_copy=True`` (little-endian hosts): container payloads become
        READ-ONLY numpy views into ``data`` — pass an ``mmap.mmap`` and the
        open is O(headers); payload bytes page in on first touch and the
        index can exceed host RAM (the reference's mmap attach,
        roaring.go:536-614 + fragment.go:179-234).  Mutations
        copy-on-write per container (Container._writable_bitmap /
        the array insert paths, which already allocate fresh arrays).
        """
        if len(data) < HEADER_SIZE:
            raise ValueError("data too small")
        raw = np.frombuffer(data, dtype=np.uint8)
        zero_copy = zero_copy and _NATIVE_LE
        head = raw[:8].view("<u4")
        if int(head[0]) != COOKIE:
            raise ValueError("invalid roaring file")
        n = int(head[1])
        bm = cls()
        hdr = raw[8 : 8 + n * 12]
        keys = hdr.reshape(n, 12)[:, :8].copy().view("<u8").ravel() if n else np.empty(0, "<u8")
        counts = (hdr.reshape(n, 12)[:, 8:12].copy().view("<u4").ravel() + 1) if n else []
        offsets = raw[8 + n * 12 : 8 + n * 16].view("<u4")
        ops_offset = HEADER_SIZE + n * 16
        for i in range(n):
            key, cnt, off = int(keys[i]), int(counts[i]), int(offsets[i])
            payload = cnt * 4 if cnt <= ARRAY_MAX_SIZE else BITMAP_N * 8
            if off >= len(data) or off + payload > len(data):
                raise ValueError(
                    f"container payload out of bounds: off={off}, need={payload}, len={len(data)}"
                )
            view = raw[off : off + payload]
            if cnt <= ARRAY_MAX_SIZE:
                arr = view.view("<u4") if zero_copy else view.view("<u4").astype(np.uint32)
                c = bm.containers[key] = Container(array=arr)
            else:
                words = view.view("<u8") if zero_copy else view.view("<u8").astype(np.uint64)
                c = bm.containers[key] = Container(bitmap=words)
                c._n = cnt  # header carries the exact cardinality
            ops_offset = off + payload
        return bm, ops_offset

    def _apply_ops(self, types: np.ndarray, values: np.ndarray) -> None:
        for typ, value in zip(types.tolist(), values.tolist()):
            value = int(value)
            if typ == OP_ADD:
                self._container_for(value).add(lowbits(value))
            else:
                c = self.containers.get(highbits(value))
                if c is not None and c.remove(lowbits(value)) and c.n == 0:
                    del self.containers[highbits(value)]
            # analysis-ok: check-then-act: Bitmap is externally synchronized (Roaring-library contract): every mutating call site holds the owning fragment's _mu
            self.op_n += 1

    @classmethod
    def from_bytes(cls, data, zero_copy: bool = False) -> "Bitmap":
        """Decode the reference format, applying any trailing op log.

        Strict: any invalid op record raises (the reference's open
        behavior, roaring.go:590-611).  Crash recovery is the caller's
        policy — see :meth:`from_bytes_recover`.  ``zero_copy``: see
        :meth:`_parse_snapshot` (pass an mmap; containers view it).
        """
        bm, ops_offset = cls._parse_snapshot(data, zero_copy=zero_copy)
        # Trailing op log (roaring.go:590-611); decoded+verified in one
        # native pass when the C++ kernels are available.
        buf = data[ops_offset:]
        if buf:
            types, values = native.oplog_decode(bytes(buf))
            bm._apply_ops(types, values)
        return bm

    @classmethod
    def from_bytes_recover(cls, data, zero_copy: bool = False) -> tuple["Bitmap", int]:
        """Crash-recovery decode: snapshot body strictly, op log leniently.

        A torn tail — the partial or checksum-corrupt record a crash
        mid-append leaves behind — stops the op replay at the last valid
        record instead of failing the open (the reference errors there and
        leaves trimming to hand repair; roaring.go:599-601 FIXME).  The
        snapshot body itself is still parsed strictly: container damage is
        real corruption, not an interrupted append, and must surface.

        Returns ``(bitmap, valid_len)`` where ``valid_len`` is the byte
        length of the recoverable file prefix (snapshot + valid ops); the
        caller truncates the file there to discard the torn tail.
        """
        bm, ops_offset = cls._parse_snapshot(data, zero_copy=zero_copy)
        buf = bytes(data[ops_offset:])
        valid_len = ops_offset
        if buf:
            types, values, valid_bytes = native.oplog_decode_prefix(buf)
            # Tear vs corruption: a crash tears only the TAIL of the log (a
            # partial final append, possibly a lost page of trailing
            # records) — it can never leave VALID records after the bad
            # one.  If any later record still checksums, record boundaries
            # are intact and a mid-log byte flipped: that destroyed acked
            # ops and must surface, not be silently truncated away.
            rest = buf[valid_bytes:]
            for i in range(13, len(rest) - 12, 13):
                try:
                    decode_op(rest[i : i + 13])
                except ValueError:
                    continue
                raise ValueError(
                    f"op log corrupt mid-stream at byte {valid_bytes} "
                    "(valid records follow the damage; refusing to truncate)"
                )
            bm._apply_ops(types, values)
            valid_len += valid_bytes
        return bm, valid_len


def _c_copy(c: Container) -> Container:
    return Container(
        array=None if c.array is None else c.array.copy(),
        bitmap=None if c.bitmap is None else c.bitmap.copy(),
    )


def _c_from_words(words: np.ndarray) -> Container:
    """Wrap a computed dense word array, demoting to an array container only
    when small (no unpack/repack round trip for dense results)."""
    n = _popcount_words(words)
    if n > ARRAY_MAX_SIZE:
        return Container(bitmap=words)
    return Container(array=_bitmap_to_values(words))


def _c_intersect(a: Container, b: Container) -> Container:
    if a.bitmap is not None and b.bitmap is not None:
        return _c_from_words(a.bitmap & b.bitmap)
    if a.is_array and b.is_array:
        return Container(array=np.intersect1d(a.array, b.array).astype(np.uint32))
    arr, bmp = (a, b) if a.is_array else (b, a)
    v = arr.array.astype(np.int64)
    mask = ((bmp.bitmap[v >> 6] >> (v & 63).astype(np.uint64)) & np.uint64(1)).astype(bool)
    return Container(array=arr.array[mask])


def _c_intersection_count(a: Container, b: Container) -> int:
    if a.bitmap is not None and b.bitmap is not None:
        return _popcount_words(a.bitmap & b.bitmap)
    if a.is_array and b.is_array:
        return len(np.intersect1d(a.array, b.array))
    arr, bmp = (a, b) if a.is_array else (b, a)
    v = arr.array.astype(np.int64)
    return int(((bmp.bitmap[v >> 6] >> (v & 63).astype(np.uint64)) & np.uint64(1)).sum())


def _c_union(a: Container, b: Container) -> Container:
    if a.bitmap is not None and b.bitmap is not None:
        return Container(bitmap=a.bitmap | b.bitmap)
    return Container.from_values(np.union1d(a.values(), b.values()))


def _c_difference(a: Container, b: Container) -> Container:
    if a.bitmap is not None and b.bitmap is not None:
        return _c_from_words(a.bitmap & ~b.bitmap)
    if a.is_array:
        if b.is_array:
            return Container(array=np.setdiff1d(a.array, b.array).astype(np.uint32))
        v = a.array.astype(np.int64)
        mask = ((b.bitmap[v >> 6] >> (v & 63).astype(np.uint64)) & np.uint64(1)).astype(bool)
        return Container(array=a.array[~mask])
    # a bitmap, b array
    out = a.bitmap.copy()
    v = b.array.astype(np.int64)
    np.bitwise_and.at(out, v >> 6, ~(np.uint64(1) << (v & 63).astype(np.uint64)))
    return _c_from_words(out)


# ---------------------------------------------------------------------------
# Op-log records (roaring.go:1560-1626)
# ---------------------------------------------------------------------------

_OP_BODY = struct.Struct("<BQ")
_OP_CHK = struct.Struct("<I")


def encode_op(typ: int, value: int) -> bytes:
    body = _OP_BODY.pack(typ, value)
    return body + _OP_CHK.pack(fnv1a32(body))


def decode_op(data: bytes) -> tuple[int, int]:
    if len(data) < OP_SIZE:
        raise ValueError(f"op data out of bounds: len={len(data)}")
    body, chk = data[:9], int(np.frombuffer(data[9:13], dtype="<u4")[0])
    if fnv1a32(body) != chk:
        raise ValueError(f"checksum mismatch: exp={fnv1a32(body):08x}, got={chk:08x}")
    typ = data[0]
    if typ not in (OP_ADD, OP_REMOVE):
        raise ValueError(f"invalid op type: {typ}")
    value = int(np.frombuffer(data[1:9], dtype="<u8")[0])
    return typ, value
